"""Task: the unit of work launched on a cluster.

Reference: sky/task.py (2212 LoC) — setup/run commands, num_nodes,
envs/secrets, workdir, file/storage mounts, resources set, service
spec, YAML round-trip with validation and ${VAR} fill-in.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.utils import common_utils

_VAR_RE = re.compile(r'\$\{\s*([A-Za-z_][A-Za-z0-9_]*)\s*\}')

CommandOrGen = Union[None, str, Callable[[int, List[str]], Optional[str]]]


def _fill_in_env_vars(yaml_field: Any, env_vars: Dict[str, str]) -> Any:
    """Substitute ${VAR} in strings recursively (reference: sky/task.py:83)."""
    if isinstance(yaml_field, str):
        return _VAR_RE.sub(
            lambda m: env_vars.get(m.group(1), m.group(0)), yaml_field)
    if isinstance(yaml_field, dict):
        return {k: _fill_in_env_vars(v, env_vars) for k, v in yaml_field.items()}
    if isinstance(yaml_field, list):
        return [_fill_in_env_vars(v, env_vars) for v in yaml_field]
    return yaml_field


class Task:
    """A coarse-grained unit of work: setup + run over N nodes."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrGen = None,
        envs: Optional[Dict[str, str]] = None,
        secrets: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
    ) -> None:
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = dict(envs or {})
        self._secrets = dict(secrets or {})
        self.num_nodes = num_nodes if num_nodes is not None else 1
        # file_mounts: {remote_path: local_path_or_cloud_uri}
        self.file_mounts: Dict[str, str] = dict(file_mounts or {})
        # volumes: {mount_path: volume_name} — named persistent volumes
        # (reference: sky/volumes/), attached+mounted at file-mount time.
        self.volumes: Dict[str, str] = {}
        # storage_mounts: {remote_path: storage_lib.Storage}
        self.storage_mounts: Dict[str, Any] = {}
        self.resources: Set[resources_lib.Resources] = {
            resources_lib.Resources()
        }
        self.service: Optional[Any] = None  # serve.S022erviceSpec
        self.best_resources: Optional[resources_lib.Resources] = None
        self.estimated_runtime: Optional[float] = None
        # Optional per-candidate runtime model for minimize=TIME
        # (reference: sky/task.py set_time_estimator — fn(Resources)->s).
        self.time_estimator_fn: Optional[Any] = None
        # Size of this task's inputs, for inter-cloud egress costing.
        self.estimated_inputs_gigabytes: Optional[float] = None
        # DAG wiring (set by Dag):
        self.dag: Optional[Any] = None
        self._validate()

    def set_time_estimator(self, fn) -> 'Task':
        """fn(resources) -> estimated seconds on that hardware."""
        self.time_estimator_fn = fn
        return self

    def estimate_runtime(self, resources: 'resources_lib.Resources') -> float:
        if self.time_estimator_fn is not None:
            return float(self.time_estimator_fn(resources))
        return float(self.estimated_runtime or 3600.0)

    def _validate(self) -> None:
        if self.name is not None:
            common_utils.check_cluster_name_is_valid(self.name.replace('_', '-')
                                                     if self.name else None)
        if self.num_nodes < 1:
            raise exceptions.InvalidTaskYAMLError(
                f'num_nodes must be >= 1, got {self.num_nodes}')
        if self.setup is not None and not isinstance(self.setup, str):
            raise exceptions.InvalidTaskYAMLError(
                'setup must be a string of commands.')
        if self.run is not None and not (isinstance(self.run, str) or
                                         callable(self.run)):
            raise exceptions.InvalidTaskYAMLError(
                'run must be a string or a per-node command generator.')
        for k in self._envs:
            if not re.fullmatch(r'[A-Za-z_][A-Za-z0-9_]*', k):
                raise exceptions.InvalidTaskYAMLError(
                    f'Invalid env var name {k!r}.')
        overlap = set(self._envs) & set(self._secrets)
        if overlap:
            raise exceptions.InvalidTaskYAMLError(
                f'envs and secrets overlap: {sorted(overlap)}')

    # -- envs ---------------------------------------------------------------
    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    @property
    def secrets(self) -> Dict[str, str]:
        return dict(self._secrets)

    @property
    def envs_and_secrets(self) -> Dict[str, str]:
        out = dict(self._envs)
        out.update(self._secrets)
        return out

    def update_envs(self, envs: Optional[Dict[str, str]]) -> 'Task':
        if envs:
            for k, v in envs.items():
                self._envs[str(k)] = str(v)
        self._validate()
        return self

    def update_secrets(self, secrets: Optional[Dict[str, str]]) -> 'Task':
        if secrets:
            for k, v in secrets.items():
                self._secrets[str(k)] = str(v)
        self._validate()
        return self

    # -- resources ----------------------------------------------------------
    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               Set[resources_lib.Resources],
                               List[resources_lib.Resources]]
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = {resources}
        self.resources = set(resources)
        return self

    def set_service(self, service: Any) -> 'Task':
        self.service = service
        return self

    def set_file_mounts(self, file_mounts: Optional[Dict[str, str]]) -> 'Task':
        self.file_mounts = dict(file_mounts or {})
        return self

    def update_file_mounts(self, file_mounts: Dict[str, str]) -> 'Task':
        self.file_mounts.update(file_mounts)
        return self

    # -- YAML round-trip ----------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None,
                         secret_overrides: Optional[Dict[str, str]] = None
                         ) -> 'Task':
        config = dict(config or {})
        # Outer schema validation: path-annotated errors with hints
        # before the strict field-by-field parse (reference:
        # sky/utils/schemas.py at the API boundary).
        from skypilot_tpu.utils import schemas
        schemas.validate_task_config(config)
        envs = dict(config.get('envs') or {})
        if env_overrides:
            envs.update(env_overrides)
        secrets = dict(config.get('secrets') or {})
        if secret_overrides:
            secrets.update(secret_overrides)
        for k, v in list(envs.items()):
            if v is None:
                v = os.environ.get(k)
                if v is None:
                    raise exceptions.InvalidTaskYAMLError(
                        f'Env var {k!r} declared with null value but not set '
                        'in the caller environment; pass --env or export it.')
                envs[k] = v
            envs[k] = str(envs[k])
        for k, v in list(secrets.items()):
            if v is None:
                v = os.environ.get(k)
                if v is None:
                    raise exceptions.InvalidTaskYAMLError(
                        f'Secret {k!r} declared with null value but not set.')
            secrets[k] = str(v)

        # ${VAR} substitution over the whole config with envs+secrets.
        config = _fill_in_env_vars(config, {**envs, **secrets})
        config['envs'] = envs
        config['secrets'] = secrets

        task = cls(
            name=config.pop('name', None),
            setup=config.pop('setup', None),
            run=config.pop('run', None),
            envs=config.pop('envs', None),
            secrets=config.pop('secrets', None),
            workdir=config.pop('workdir', None),
            num_nodes=config.pop('num_nodes', None),
            file_mounts=None,
        )
        file_mounts = config.pop('file_mounts', None) or {}
        plain: Dict[str, str] = {}
        for dst, src in file_mounts.items():
            if isinstance(src, dict):
                # Inline storage spec: {name:, source:, mode:, store:}
                from skypilot_tpu.data import storage as storage_lib
                task.storage_mounts[dst] = storage_lib.Storage.from_yaml_config(
                    src)
            else:
                plain[dst] = src
        task.set_file_mounts(plain)

        resources_config = config.pop('resources', None)
        task.set_resources(
            resources_lib.Resources.from_yaml_config(resources_config))

        volumes = config.pop('volumes', None) or {}
        if not isinstance(volumes, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in volumes.items()):
            raise exceptions.InvalidTaskYAMLError(
                'volumes must map mount_path -> volume name.')
        task.volumes = dict(volumes)

        service = config.pop('service', None)
        if service is not None:
            from skypilot_tpu.serve import service_spec
            task.set_service(service_spec.SkyServiceSpec.from_yaml_config(
                service))
        config.pop('config', None)  # per-task config overrides handled upstream
        experimental = config.pop('experimental', None)
        del experimental
        if config:
            raise exceptions.InvalidTaskYAMLError(
                f'Unknown task fields: {sorted(config)}')
        return task

    @classmethod
    def from_yaml(cls, yaml_path: str) -> 'Task':
        configs = common_utils.read_yaml_all(os.path.expanduser(yaml_path))
        configs = [c for c in configs if c is not None]
        if not configs:
            return cls()
        if len(configs) > 1:
            raise exceptions.InvalidTaskYAMLError(
                'Multiple YAML documents: use Dag.from_yaml for chains.')
        return cls.from_yaml_config(configs[0])

    def to_yaml_config(self, redact_secrets: bool = False) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None and value != {} and value != []:
                config[key] = value

        add('name', self.name)
        if len(self.resources) == 1:
            add('resources', next(iter(self.resources)).to_yaml_config())
        else:
            add('resources',
                {'any_of': [r.to_yaml_config() for r in self.resources]})
        if self.num_nodes != 1:
            add('num_nodes', self.num_nodes)
        add('workdir', self.workdir)
        add('setup', self.setup)
        add('run', self.run if isinstance(self.run, str) else None)
        add('envs', self._envs or None)
        if self._secrets:
            add('secrets', {k: ('<redacted>' if redact_secrets else v)
                            for k, v in self._secrets.items()})
        mounts: Dict[str, Any] = dict(self.file_mounts)
        for dst, store in self.storage_mounts.items():
            mounts[dst] = store.to_yaml_config()
        add('file_mounts', mounts or None)
        add('volumes', dict(self.volumes) or None)
        if self.service is not None:
            add('service', self.service.to_yaml_config())
        return config

    # -- misc ---------------------------------------------------------------
    def __rshift__(self, other: 'Task') -> 'Task':
        """task_a >> task_b adds an edge in the current Dag context."""
        from skypilot_tpu import dag as dag_lib
        dag = dag_lib.get_current_dag()
        if dag is None:
            raise RuntimeError('task_a >> task_b requires a `with Dag():` '
                               'context.')
        dag.add_edge(self, other)
        return other

    def __repr__(self) -> str:
        label = self.name or 'unnamed'
        r = next(iter(self.resources)) if self.resources else None
        return f'Task({label!r}, num_nodes={self.num_nodes}, {r})'
