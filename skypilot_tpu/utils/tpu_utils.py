"""TPU accelerator naming, slice topology, and host math.

This is the TPU-first core the reference lacks: the reference treats a
TPU type as an opaque accelerator string and hardcodes host shapes
(`sky/clouds/utils/gcp_utils.py:30-56` — "pod slice = name not ending
in -8"; `sky/clouds/gcp.py:770-823` — hardcoded host vCPU/mem). Here
slice topology (chips/host, hosts/slice, ICI torus shape) is modeled
explicitly so the optimizer, provisioner, and gang executor can reason
about hosts and ICI domains.

Naming convention (GCP):
  - v2/v3/v4/v5p: suffix counts TensorCores; chips = suffix / 2.
  - v5e (v5litepod) / v6e: suffix counts chips.
Host shapes:
  - v4/v5p: 4 chips per host, 3D torus ICI.
  - v5e/v6e: up to 8 chips per host (2x4), 2D torus ICI.
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

TPU_VERSIONS = ('v2', 'v3', 'v4', 'v5e', 'v5p', 'v6e')

# version -> (cores_per_chip, max_chips_per_host, ici_dims,
#             host_chip_shape, suffix_counts_chips)
_VERSION_INFO: Dict[str, Tuple[int, int, int, Tuple[int, ...], bool]] = {
    'v2': (2, 4, 2, (2, 2), False),
    'v3': (2, 4, 2, (2, 2), False),
    'v4': (2, 4, 3, (2, 2, 1), False),
    'v5p': (2, 4, 3, (2, 2, 1), False),
    'v5e': (1, 8, 2, (2, 4), True),
    'v6e': (1, 8, 2, (2, 4), True),
}

# Host VM shape behind each TPU host (vCPUs, memory GiB). The reference
# hardcodes these in sky/clouds/gcp.py:770-823; we keep them per-version.
_HOST_VM: Dict[str, Tuple[int, int]] = {
    'v2': (96, 334),
    'v3': (96, 334),
    'v4': (240, 407),
    'v5p': (208, 448),
    'v5e': (224, 384),
    'v6e': (180, 720),
}

_TPU_NAME_RE = re.compile(r'^tpu-(v\d+[a-z]*)-(\d+)$')


@dataclasses.dataclass(frozen=True)
class TpuSliceSpec:
    """Static description of one TPU slice type (e.g. tpu-v5p-128)."""
    name: str                # canonical accelerator name, e.g. 'tpu-v5p-128'
    version: str             # 'v5p'
    suffix: int              # the numeric suffix (cores or chips)
    num_chips: int
    chips_per_host: int
    num_hosts: int
    topology: Tuple[int, ...]   # ICI torus shape in chips, e.g. (4, 4, 4)
    cores_per_chip: int

    @property
    def is_pod_slice(self) -> bool:
        """Multi-host slice (one Task "node" spans num_hosts VMs)."""
        return self.num_hosts > 1

    @property
    def topology_str(self) -> str:
        return 'x'.join(str(d) for d in self.topology)

    @property
    def num_cores(self) -> int:
        return self.num_chips * self.cores_per_chip

    def host_vm_shape(self) -> Tuple[int, int]:
        return _HOST_VM[self.version]

    def gcp_accelerator_type(self) -> str:
        """The acceleratorType string for the GCP TPU API.

        v5e is named 'v5litepod-N' in the API; others are 'vX-N' with N
        counting cores.
        """
        if self.version == 'v5e':
            return f'v5litepod-{self.num_chips}'
        if self.version == 'v6e':
            return f'v6e-{self.num_chips}'
        return f'{self.version}-{self.num_cores}'


def parse_tpu_name(acc_name: str) -> Optional[Tuple[str, int]]:
    """'tpu-v5p-128' -> ('v5p', 128); None if not a TPU accelerator."""
    m = _TPU_NAME_RE.match(acc_name.lower())
    if m is None:
        return None
    version, suffix = m.group(1), int(m.group(2))
    if version not in _VERSION_INFO:
        raise ValueError(
            f'Unknown TPU version {version!r} in {acc_name!r}; '
            f'known: {list(_VERSION_INFO)}')
    return version, suffix


def is_tpu(acc_name: Optional[str]) -> bool:
    if acc_name is None:
        return False
    return _TPU_NAME_RE.match(acc_name.lower()) is not None


def _default_topology(version: str, num_chips: int) -> Tuple[int, ...]:
    """Most-cubic torus shape for the chip count.

    v4/v5p slices are 3D tori with each dim a multiple of 4 above one
    host (GCP accepts e.g. 2x2x1, 2x2x2, 2x2x4, 4x4x4, 4x4x8...);
    v5e/v6e are 2D (2x2, 2x4, 4x4, 4x8, 8x8, 8x16, 16x16).
    """
    _, _, dims, _, _ = _VERSION_INFO[version]
    if dims == 2:
        x = 2 ** math.floor(math.log2(math.isqrt(num_chips)))
        x = max(1, x)
        while num_chips % x != 0:
            x //= 2
        return (x, num_chips // x)
    # 3D: factor into (a, b, c) as cubic as possible with powers of 2
    # (and 4-multiples for large slices — we accept near-cubic shapes).
    best = (1, 1, num_chips)
    best_score = float('inf')
    a = 1
    while a * a * a <= num_chips:
        if num_chips % a == 0:
            rem = num_chips // a
            b = a
            while b * b <= rem:
                if rem % b == 0:
                    c = rem // b
                    score = (c - a)  # minimize spread
                    if score < best_score:
                        best, best_score = (a, b, c), score
                b += 1
        a += 1
    return best


def get_slice_spec(acc_name: str,
                   topology: Optional[str] = None) -> TpuSliceSpec:
    """Resolve an accelerator name (+optional topology override) to a spec.

    Raises InvalidResourcesError-compatible ValueError on bad input.
    """
    parsed = parse_tpu_name(acc_name)
    if parsed is None:
        raise ValueError(f'{acc_name!r} is not a TPU accelerator name '
                         '(expect tpu-<version>-<N>).')
    version, suffix = parsed
    cores_per_chip, max_cph, dims, _, suffix_is_chips = _VERSION_INFO[version]
    num_chips = suffix if suffix_is_chips else suffix // cores_per_chip
    if num_chips < 1:
        raise ValueError(f'{acc_name!r}: invalid size suffix {suffix}.')

    if topology is not None:
        topo = tuple(int(d) for d in topology.lower().split('x'))
        if len(topo) != dims and math.prod(topo) != num_chips:
            raise ValueError(
                f'Topology {topology!r} invalid for {acc_name!r}: expect '
                f'{dims}D torus with {num_chips} chips.')
        if math.prod(topo) != num_chips:
            raise ValueError(
                f'Topology {topology!r} has {math.prod(topo)} chips; '
                f'{acc_name!r} has {num_chips}.')
    else:
        topo = _default_topology(version, num_chips)

    chips_per_host = min(max_cph, num_chips)
    num_hosts = max(1, math.ceil(num_chips / max_cph))
    return TpuSliceSpec(name=f'tpu-{version}-{suffix}', version=version,
                        suffix=suffix, num_chips=num_chips,
                        chips_per_host=chips_per_host, num_hosts=num_hosts,
                        topology=topo, cores_per_chip=cores_per_chip)


def standard_slice_sizes(version: str) -> List[int]:
    """Suffixes of the slice sizes offered for a version (for the catalog)."""
    cores_per_chip, max_cph, dims, _, suffix_is_chips = _VERSION_INFO[version]
    if version == 'v5e':
        chips = [1, 4, 8, 16, 32, 64, 128, 256]
    elif version == 'v6e':
        chips = [1, 4, 8, 16, 32, 64, 128, 256]
    elif version == 'v5p':
        chips = [4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 3072]
    elif version == 'v4':
        chips = [4, 8, 16, 32, 64, 128, 256, 512, 1024]
    else:  # v2/v3
        chips = [4, 16, 32, 128]
    if suffix_is_chips:
        return chips
    return [c * cores_per_chip for c in chips]
