"""Minimal kubeconfig loader: enough to call the k8s REST API.

Supports bearer-token and client-certificate auth entries plus CA /
insecure-skip-verify; exec-plugin credentials (gke-gcloud-auth-plugin)
are resolved by running the plugin once. The reference uses the
official client (sky/adaptors/kubernetes.py); this build keeps the
dependency surface to requests.
"""
from __future__ import annotations

import base64
import json
import os
import subprocess
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import yaml

DEFAULT_PATH = '~/.kube/config'


class KubeContext:

    def __init__(self, name: str, server: str,
                 token: Optional[str] = None,
                 ca_data: Optional[bytes] = None,
                 client_cert: Optional[bytes] = None,
                 client_key: Optional[bytes] = None,
                 insecure: bool = False,
                 namespace: str = 'default') -> None:
        self.name = name
        self.server = server.rstrip('/')
        self.token = token
        self.insecure = insecure
        self.namespace = namespace
        self._ca_file = self._tmp(ca_data, '.ca.crt')
        self._cert_file = self._tmp(client_cert, '.client.crt')
        self._key_file = self._tmp(client_key, '.client.key')

    @staticmethod
    def _tmp(data: Optional[bytes], suffix: str) -> Optional[str]:
        if not data:
            return None
        f = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
        f.write(data)
        f.close()
        return f.name

    # -- requests kwargs -----------------------------------------------------
    def request_kwargs(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        headers = {}
        if self.token:
            headers['Authorization'] = f'Bearer {self.token}'
        out['headers'] = headers
        if self.insecure:
            out['verify'] = False
        elif self._ca_file:
            out['verify'] = self._ca_file
        if self._cert_file and self._key_file:
            out['cert'] = (self._cert_file, self._key_file)
        return out


def _b64(field: Optional[str]) -> Optional[bytes]:
    return base64.b64decode(field) if field else None


def _resolve_exec_token(exec_spec: Dict[str, Any]) -> Optional[str]:
    cmd = [exec_spec['command'], *exec_spec.get('args', [])]
    env = dict(os.environ)
    for item in exec_spec.get('env') or []:
        env[item['name']] = item['value']
    try:
        out = subprocess.run(cmd, env=env, capture_output=True, check=True,
                             timeout=30).stdout
        cred = json.loads(out)
        return cred.get('status', {}).get('token')
    except (subprocess.SubprocessError, OSError, ValueError):
        return None


def load_contexts(path: str = DEFAULT_PATH) -> List[str]:
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return []
    with open(path, 'r', encoding='utf-8') as f:
        config = yaml.safe_load(f) or {}
    return [c['name'] for c in config.get('contexts', [])]


def load_context(context_name: Optional[str] = None,
                 path: str = DEFAULT_PATH) -> Optional[KubeContext]:
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return None
    with open(path, 'r', encoding='utf-8') as f:
        config = yaml.safe_load(f) or {}
    context_name = context_name or config.get('current-context')
    if not context_name:
        return None
    ctx_entry = next((c for c in config.get('contexts', [])
                      if c['name'] == context_name), None)
    if ctx_entry is None:
        return None
    cluster_name = ctx_entry['context']['cluster']
    user_name = ctx_entry['context']['user']
    namespace = ctx_entry['context'].get('namespace', 'default')
    cluster = next((c['cluster'] for c in config.get('clusters', [])
                    if c['name'] == cluster_name), None)
    user = next((u['user'] for u in config.get('users', [])
                 if u['name'] == user_name), {})
    if cluster is None:
        return None
    token = user.get('token')
    if token is None and 'exec' in user:
        token = _resolve_exec_token(user['exec'])
    return KubeContext(
        name=context_name,
        server=cluster['server'],
        token=token,
        ca_data=_b64(cluster.get('certificate-authority-data')),
        client_cert=_b64(user.get('client-certificate-data')),
        client_key=_b64(user.get('client-key-data')),
        insecure=bool(cluster.get('insecure-skip-tls-verify')),
        namespace=namespace,
    )
