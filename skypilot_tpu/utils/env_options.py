"""Environment-variable toggles (reference: sky/utils/env_options.py)."""
from __future__ import annotations

import enum
import os


class Options(enum.Enum):
    IS_DEVELOPER = 'SKYPILOT_DEV'
    SHOW_DEBUG_INFO = 'SKYPILOT_DEBUG'
    DISABLE_LOGGING = 'SKYPILOT_DISABLE_USAGE_COLLECTION'
    MINIMIZE_LOGGING = 'SKYPILOT_MINIMIZE_LOGGING'
    SUPPRESS_SENSITIVE_LOG = 'SKYPILOT_SUPPRESS_SENSITIVE_LOG'

    def get(self) -> bool:
        return os.environ.get(self.value, 'False').lower() in (
            '1', 'true', 'yes')

    def __bool__(self) -> bool:
        return self.get()
