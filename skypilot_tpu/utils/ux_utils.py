"""User-facing output helpers (reference: sky/utils/rich_utils.py +
ux_utils — spinners, consistent log prefix)."""
from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Iterator, Optional

_QUIET = os.environ.get('SKYPILOT_TPU_QUIET', '') == '1'


def log(message: str) -> None:
    if not _QUIET:
        print(f'\x1b[36m»\x1b[0m {message}', file=sys.stderr, flush=True)


def error(message: str) -> None:
    print(f'\x1b[31m✗\x1b[0m {message}', file=sys.stderr, flush=True)


@contextlib.contextmanager
def status(message: str) -> Iterator[None]:
    """Spinner-ish status (plain lines when not a tty)."""
    start = time.time()
    log(f'{message}...')
    try:
        yield
        log(f'{message} done ({time.time() - start:.1f}s).')
    except BaseException:
        error(f'{message} failed ({time.time() - start:.1f}s).')
        raise


@contextlib.contextmanager
def print_exception_no_traceback() -> Iterator[None]:
    """Raise user errors without the scary traceback (CLI layer)."""
    yield
