"""Parse/format `infra: cloud/region/zone` strings.

Reference: sky/utils/infra_utils.py (`gcp/us-central2/us-central2-b`;
`k8s/context` for kubernetes).
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class InfraInfo:
    cloud: Optional[str] = None
    region: Optional[str] = None
    zone: Optional[str] = None

    @classmethod
    def from_str(cls, infra: Optional[str]) -> 'InfraInfo':
        if infra is None or infra.strip() == '':
            return cls()
        parts = [p.strip() for p in infra.strip('/').split('/')]
        wildcard = lambda s: None if s in ('*', '') else s
        cloud = wildcard(parts[0]) if parts else None
        if cloud is not None and cloud.lower() in ('k8s', 'kubernetes'):
            # k8s/context-name — context may itself contain '/'
            context = '/'.join(parts[1:]) if len(parts) > 1 else None
            return cls(cloud='kubernetes', region=context, zone=None)
        region = wildcard(parts[1]) if len(parts) > 1 else None
        zone = wildcard(parts[2]) if len(parts) > 2 else None
        if len(parts) > 3:
            raise ValueError(f'Invalid infra string: {infra!r} '
                             '(expect cloud[/region[/zone]])')
        return cls(cloud=cloud, region=region, zone=zone)

    def to_str(self) -> Optional[str]:
        parts = []
        for p in (self.cloud, self.region, self.zone):
            parts.append(p if p is not None else '*')
        while parts and parts[-1] == '*':
            parts.pop()
        if not parts:
            return None
        return '/'.join(parts)

    def formatted_str(self) -> str:
        return self.to_str() or '-'
