"""Per-request identity context inside executor worker processes.

Reference analog: sky/utils/context.py's request context. Each API
request runs in its own forked worker (server/requests/executor.py),
so a module global is a faithful per-request scope — no contextvars
or async propagation needed.
"""
from __future__ import annotations

from typing import Optional

_request_user: Optional[str] = None


def set_request_user(user: Optional[str]) -> None:
    global _request_user
    _request_user = user if user and user != 'unknown' else None


def get_request_user() -> Optional[str]:
    return _request_user
