"""Subprocess helpers: parallel fan-out, process-tree kill, daemonize.

Reference: sky/utils/subprocess_utils.py.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence, Tuple

import psutil


def run_in_parallel(func: Callable, args: Sequence[Any],
                    num_threads: Optional[int] = None) -> List[Any]:
    """Apply func over args with a thread pool (SSH fan-out pattern)."""
    if not args:
        return []
    if len(args) == 1:
        return [func(args[0])]
    workers = num_threads or min(32, len(args))
    with ThreadPoolExecutor(max_workers=workers) as pool:
        return list(pool.map(func, args))


def kill_process_tree(pid: int, include_parent: bool = True,
                      sig: int = signal.SIGTERM) -> None:
    try:
        parent = psutil.Process(pid)
    except psutil.NoSuchProcess:
        return
    children = parent.children(recursive=True)
    for proc in children:
        try:
            proc.send_signal(sig)
        except psutil.NoSuchProcess:
            pass
    if include_parent:
        try:
            parent.send_signal(sig)
        except psutil.NoSuchProcess:
            pass


def kill_children_processes(parent_pid: Optional[int] = None,
                            force: bool = False) -> None:
    kill_process_tree(parent_pid or os.getpid(), include_parent=False,
                      sig=signal.SIGKILL if force else signal.SIGTERM)


# Resolved at import, NOT inside preexec_fn: the child of a fork from
# a multi-threaded launcher must not import (import-lock deadlock).
try:
    import ctypes as _ctypes
    _libc = _ctypes.CDLL('libc.so.6', use_errno=True)
except OSError:  # non-glibc platform
    _libc = None


def _pdeathsig_preexec() -> None:
    """PR_SET_PDEATHSIG(SIGTERM): die when the parent does. Test-only:
    a killed pytest run must not leak agents/controllers/replica
    servers; production daemons must SURVIVE their launcher, so this
    is never the default."""
    if _libc is not None:
        _libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG == 1


def launch_daemon(cmd: List[str], log_path: str,
                  env: Optional[dict] = None,
                  cwd: Optional[str] = None) -> int:
    """Start a detached daemon process; returns pid.

    SKYPILOT_DAEMON_PDEATHSIG holds the PID of the process daemons
    should die with (the test runner sets it to its own pid). The
    parent-death tie applies ONLY when the CURRENT process is that
    pid: daemons launched by intermediaries — ephemeral request
    workers, controllers, the API server — must keep production
    semantics (a cluster agent must survive its launch request; a
    kill-9'd controller's cluster must still be adoptable)."""
    os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
    preexec = (_pdeathsig_preexec
               if os.environ.get('SKYPILOT_DAEMON_PDEATHSIG') ==
               str(os.getpid()) else None)
    with open(log_path, 'ab') as log_file:
        proc = subprocess.Popen(
            cmd,
            stdout=log_file,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            env=env,
            cwd=cwd,
            start_new_session=True,
            preexec_fn=preexec,
        )
    return proc.pid


def process_alive(pid: int) -> bool:
    if pid is None or pid <= 0:
        return False
    try:
        proc = psutil.Process(pid)
        return proc.is_running() and proc.status() != psutil.STATUS_ZOMBIE
    except psutil.NoSuchProcess:
        return False
