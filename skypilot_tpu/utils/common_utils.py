"""Small shared helpers: ids, user, validation, json/yaml dump.

Reference analog: sky/utils/common_utils.py.
"""
from __future__ import annotations

import getpass
import hashlib
import json
import os
import random
import re
import socket
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

import yaml

CLUSTER_NAME_VALID_REGEX = re.compile(r'^[a-zA-Z]([-a-zA-Z0-9]*[a-zA-Z0-9])?$')

_usage_run_id: Optional[str] = None


def get_usage_run_id() -> str:
    global _usage_run_id
    if _usage_run_id is None:
        _usage_run_id = str(uuid.uuid4())
    return _usage_run_id


def get_user_hash() -> str:
    """Stable 8-hex-char id of the local user (reference: user_hash)."""
    env = os.environ.get('SKYPILOT_USER_ID')
    if env:
        return env
    user = f'{getpass.getuser()}-{socket.gethostname()}'
    return hashlib.md5(user.encode()).hexdigest()[:8]


def get_user_name() -> str:
    return os.environ.get('SKYPILOT_USER', None) or getpass.getuser()


def base36(n: int) -> str:
    chars = '0123456789abcdefghijklmnopqrstuvwxyz'
    if n == 0:
        return '0'
    out = []
    while n:
        n, r = divmod(n, 36)
        out.append(chars[r])
    return ''.join(reversed(out))


def fresh_cluster_name(prefix: str = 'sky') -> str:
    return f'{prefix}-{base36(int(time.time()))[-4:]}{base36(uuid.uuid4().int)[:2]}'


def check_cluster_name_is_valid(name: Optional[str]) -> None:
    if name is None:
        return
    if not CLUSTER_NAME_VALID_REGEX.match(name):
        raise ValueError(
            f'Cluster name {name!r} is invalid: must match '
            f'{CLUSTER_NAME_VALID_REGEX.pattern} (letters, digits, dashes; '
            'start with a letter).')
    if len(name) > 56:
        raise ValueError(f'Cluster name {name!r} too long (max 56 chars).')


def make_cluster_name_on_cloud(display_name: str, max_length: int = 35,
                               add_user_hash: bool = True) -> str:
    """Cloud-safe cluster name: lowercase, user-hash suffixed, truncated.

    Reference: common_utils.make_cluster_name_on_cloud.
    """
    name = re.sub(r'[^a-z0-9-]', '-', display_name.lower())
    suffix = f'-{get_user_hash()}' if add_user_hash else ''
    if len(name) + len(suffix) > max_length:
        digest = hashlib.md5(name.encode()).hexdigest()[:4]
        name = name[:max_length - len(suffix) - 5] + '-' + digest
    return name + suffix


def read_yaml(path: str) -> Dict[str, Any]:
    with open(path, 'r', encoding='utf-8') as f:
        return yaml.safe_load(f)


def read_yaml_all(path: str) -> List[Dict[str, Any]]:
    with open(path, 'r', encoding='utf-8') as f:
        return list(yaml.safe_load_all(f))


def dump_yaml(path: str, config: Union[Dict, List[Dict]]) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        f.write(dump_yaml_str(config))


def dump_yaml_str(config: Union[Dict, List[Dict]]) -> str:

    class _Dumper(yaml.SafeDumper):
        pass

    _Dumper.add_representer(
        type(None),
        lambda dumper, _: dumper.represent_scalar('tag:yaml.org,2002:null', 'null'))
    if isinstance(config, list):
        return yaml.dump_all(config, Dumper=_Dumper, sort_keys=False,
                             default_flow_style=False)
    return yaml.dump(config, Dumper=_Dumper, sort_keys=False,
                     default_flow_style=False)


def format_exception(e: BaseException, use_bracket: bool = False) -> str:
    name = type(e).__name__
    if use_bracket:
        return f'[{name}] {e}'
    return f'{name}: {e}'


def class_fullname(cls: type) -> str:
    return f'{cls.__module__}.{cls.__name__}'


def remove_color(s: str) -> str:
    return re.sub(r'\x1b\[\d+(;\d+)*m', '', s)


def truncate_long_string(s: str, max_length: int = 35) -> str:
    if len(s) <= max_length:
        return s
    return s[:max_length - 3] + '...'


def parse_memory(mem: Union[str, int, float, None]) -> Optional[float]:
    """'16', '16+', '16GB' → 16.0 (GiB). '+' handled by caller via str."""
    if mem is None:
        return None
    s = str(mem).strip().rstrip('+').lower()
    for suffix, mult in (('gb', 1), ('g', 1), ('tb', 1024), ('t', 1024)):
        if s.endswith(suffix):
            return float(s[:-len(suffix)]) * mult
    return float(s)


def retry(func: Optional[Callable] = None, *, max_retries: int = 3,
          initial_backoff: float = 1.0) -> Callable:
    """Simple exponential-backoff retry decorator."""

    def wrap(f: Callable) -> Callable:

        def inner(*args, **kwargs):
            backoff = initial_backoff
            for attempt in range(max_retries):
                try:
                    return f(*args, **kwargs)
                except Exception:  # pylint: disable=broad-except
                    if attempt == max_retries - 1:
                        raise
                    time.sleep(backoff)
                    backoff *= 2

        inner.__name__ = f.__name__
        return inner

    if func is not None:
        return wrap(func)
    return wrap


def json_dumps_compact(obj: Any) -> str:
    return json.dumps(obj, separators=(',', ':'), sort_keys=True)


class Backoff:
    """Exponential backoff (reference: common_utils.Backoff).

    With `jitter=True`, uses DECORRELATED jitter (sleep_n =
    min(cap, U(initial, 3 * sleep_{n-1}))): retriers that failed
    together spread out instead of re-colliding every multiplier
    period — the thundering-herd shape of zone-wide preemption
    relaunches. Pass a seeded `rng` for reproducible schedules
    (chaos tests)."""

    def __init__(self, initial: float = 5.0, max_backoff: float = 60.0,
                 multiplier: float = 1.6, jitter: bool = False,
                 rng: Optional[Any] = None):
        self._initial = initial
        self._max = max_backoff
        self._mult = multiplier
        self._current = initial
        self._jitter = jitter
        self._rng = rng if rng is not None else random.Random()

    def current_backoff(self) -> float:
        if self._jitter:
            cur = min(self._max,
                      self._rng.uniform(self._initial,
                                        self._current * 3.0))
            self._current = cur
            return cur
        cur = self._current
        self._current = min(self._current * self._mult, self._max)
        return cur
