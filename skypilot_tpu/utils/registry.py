"""Name → implementation registries for clouds and strategies.

Reference pattern: sky/utils/registry.py (clouds, jobs recovery
strategies registered by decorator, looked up case-insensitively).
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):

    def __init__(self, registry_name: str) -> None:
        self._name = registry_name
        self._registry: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}
        self._default: Optional[str] = None

    def register(self, name: Optional[str] = None,
                 aliases: Optional[List[str]] = None,
                 default: bool = False) -> Callable[[Type], Type]:

        def decorator(cls: Type) -> Type:
            key = (name or cls.__name__).lower()
            if key in self._registry:
                raise ValueError(
                    f'{self._name} {key!r} is already registered.')
            self._registry[key] = cls
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            if default:
                self._default = key
            return cls

        return decorator

    def from_str(self, name: Optional[str]) -> Optional[T]:
        if name is None:
            return None
        key = name.lower()
        key = self._aliases.get(key, key)
        if key not in self._registry:
            raise ValueError(
                f'{self._name} {name!r} not found; registered: '
                f'{sorted(self._registry)}')
        return self._registry[key]

    def get(self, name: str) -> Optional[T]:
        key = name.lower()
        key = self._aliases.get(key, key)
        return self._registry.get(key)

    @property
    def default(self) -> Optional[str]:
        return self._default

    def keys(self) -> List[str]:
        return sorted(self._registry)

    def values(self) -> List[T]:
        return [self._registry[k] for k in sorted(self._registry)]


# Instantiated lazily by the modules that own them:
CLOUD_REGISTRY: 'Registry' = Registry('Cloud')
JOBS_RECOVERY_STRATEGY_REGISTRY: 'Registry' = Registry('JobsRecoveryStrategy')
AUTOSCALER_REGISTRY: 'Registry' = Registry('Autoscaler')
LB_POLICY_REGISTRY: 'Registry' = Registry('LoadBalancingPolicy')
