"""File locks (reference: sky/utils/locks.py — file + DB locks)."""
from __future__ import annotations

import os

import filelock


class FileLock:
    """filelock wrapper that creates parent dirs."""

    def __init__(self, path: str, timeout: float = 60.0) -> None:
        path = os.path.expanduser(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._lock = filelock.FileLock(path, timeout=timeout)

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *args) -> None:
        self._lock.release()
