"""Chrome trace-event tracing.

Reference: sky/utils/timeline.py — JSON trace written when
SKYPILOT_TIMELINE_FILE_PATH is set; `@timeline.event` marks hot
functions. Load the output in chrome://tracing or Perfetto.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional, Union

_events: List[dict] = []
_lock = threading.Lock()
_enabled_path: Optional[str] = None
_saved = False  # a save() happened and no event has landed since


def _init() -> None:
    global _enabled_path
    _enabled_path = os.environ.get('SKYPILOT_TIMELINE_FILE_PATH')
    if _enabled_path:
        atexit.register(save)


def enabled() -> bool:
    return _enabled_path is not None


def enable(path: str) -> None:
    """Programmatic enable (e.g. `train_lm --trace-file`): same effect
    as exporting SKYPILOT_TIMELINE_FILE_PATH before launch — events
    collect from now on and flush to `path` at exit (or on save())."""
    global _enabled_path
    already = _enabled_path is not None
    _enabled_path = path
    if not already:
        atexit.register(save)


class Event:
    """Context manager emitting a complete ('X') trace event."""

    def __init__(self, name: str, message: Optional[str] = None) -> None:
        self._name = name
        self._message = message
        self._start = 0.0

    def __enter__(self) -> 'Event':
        self._start = time.perf_counter()
        return self

    def __exit__(self, *args) -> None:
        global _saved
        if _enabled_path is None:
            return
        end = time.perf_counter()
        with _lock:
            if _saved:
                # The buffer was flushed by an explicit save(); keep
                # collecting into a fresh trace (a later save()
                # rewrites the file) but say so once — callers that
                # meant to stop tracing should have cleared the env /
                # not re-entered Event.
                _saved = False
                from skypilot_tpu.utils import ux_utils
                ux_utils.log(
                    f'timeline: events recorded after save(); '
                    f'starting a fresh trace buffer for '
                    f'{_enabled_path} (the next save() overwrites '
                    f'it).')
            _events.append({
                'name': self._name,
                'cat': 'skypilot_tpu',
                'ph': 'X',
                'ts': self._start * 1e6,
                'dur': (end - self._start) * 1e6,
                'pid': os.getpid(),
                'tid': threading.get_ident() % 100000,
                'args': {'message': self._message} if self._message else {},
            })


def event(fn_or_name: Union[Callable, str]) -> Callable:
    """Decorator form: @timeline.event or @timeline.event('name')."""

    def decorate(fn: Callable, name: str) -> Callable:

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if _enabled_path is None:
                return fn(*args, **kwargs)
            with Event(name):
                return fn(*args, **kwargs)

        return wrapper

    if callable(fn_or_name):
        return decorate(fn_or_name, getattr(fn_or_name, '__qualname__',
                                            fn_or_name.__name__))
    return lambda fn: decorate(fn, fn_or_name)


def save() -> None:
    """Flush collected events to the trace file and clear the
    buffer, so the module is cleanly reusable (a second enable()/
    save() cycle writes a fresh trace instead of duplicating the
    first one). Events recorded after a save() log one warning and
    start the next buffer — they are no longer silently stranded."""
    global _saved
    if _enabled_path is None or not _events:
        return
    path = os.path.expanduser(_enabled_path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with _lock:
        payload = {'traceEvents': list(_events)}
        _events.clear()
        _saved = True
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f)


_init()
