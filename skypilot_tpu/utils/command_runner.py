"""Command runners: run commands / rsync files on cluster hosts.

Reference: sky/utils/command_runner.py (2203 LoC — SSH/K8s/Slurm/Local
runners with rsync, ControlMaster, port-forward). This build ships the
two runners the TPU path needs:
  - SSHCommandRunner: TPU-VM hosts over ssh/rsync with ControlMaster
    multiplexing (one TCP conn per host reused across the many
    bootstrap commands).
  - LocalSandboxRunner: a "host" that is a local directory + process,
    backing the Local cloud (tests/CI; no cloud account).
"""
from __future__ import annotations

import os
import shlex
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions

_DEFAULT_SSH_OPTIONS = [
    '-o', 'StrictHostKeyChecking=no',
    '-o', 'UserKnownHostsFile=/dev/null',
    '-o', 'IdentitiesOnly=yes',
    '-o', 'ConnectTimeout=30',
    '-o', 'ServerAliveInterval=20',
    '-o', 'ServerAliveCountMax=3',
    '-o', 'LogLevel=ERROR',
]


def _control_path() -> str:
    d = os.path.join(tempfile.gettempdir(), 'skypilot_tpu_ssh_cm')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, '%C')


class CommandRunner:
    """Run shell commands and sync files on one remote host."""

    def __init__(self, node_id: str) -> None:
        self.node_id = node_id

    # returns (returncode, stdout, stderr) when require_outputs else rc
    def run(self, cmd: Union[str, List[str]], *,
            require_outputs: bool = False,
            stream_logs: bool = False,
            log_path: Optional[str] = None,
            env: Optional[Dict[str, str]] = None,
            timeout: Optional[float] = None,
            ) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              excludes: Optional[List[str]] = None) -> None:
        raise NotImplementedError

    def check_connection(self) -> bool:
        try:
            rc = self.run('true', timeout=15)
            return rc == 0
        except Exception:  # pylint: disable=broad-except
            return False

    def interactive_shell_argv(self) -> Tuple[List[str],
                                              Optional[Dict[str, str]],
                                              Optional[str]]:
        """(argv, env, cwd) for an interactive login shell on this
        host — what the websocket attach endpoint runs under a PTY
        (reference: the server's websocket SSH tunnel)."""
        raise NotImplementedError

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _exec(cmd: List[str], *, require_outputs: bool, stream_logs: bool,
              log_path: Optional[str], timeout: Optional[float],
              env: Optional[Dict[str, str]] = None,
              cwd: Optional[str] = None
              ) -> Union[int, Tuple[int, str, str]]:
        stdout_chunks: List[str] = []
        stderr_chunks: List[str] = []
        log_file = None
        if log_path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(log_path)),
                        exist_ok=True)
            log_file = open(log_path, 'ab')
        try:
            proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, cwd=cwd)
            assert proc.stdout is not None
            import time as _time
            deadline = _time.time() + timeout if timeout else None
            for raw in iter(proc.stdout.readline, b''):
                if deadline and _time.time() > deadline:
                    proc.kill()
                    raise subprocess.TimeoutExpired(cmd, timeout)
                line = raw.decode('utf-8', errors='replace')
                stdout_chunks.append(line)
                if stream_logs:
                    print(line, end='', flush=True)
                if log_file is not None:
                    log_file.write(raw)
                    log_file.flush()
            proc.wait(timeout=timeout)
        finally:
            if log_file is not None:
                log_file.close()
        if require_outputs:
            return proc.returncode, ''.join(stdout_chunks), \
                ''.join(stderr_chunks)
        return proc.returncode


class SSHCommandRunner(CommandRunner):
    """ssh/rsync to one host, with ControlMaster connection reuse."""

    def __init__(self, node: Tuple[str, int], ssh_user: str,
                 ssh_private_key: str,
                 ssh_proxy_command: Optional[str] = None) -> None:
        ip, port = node
        super().__init__(f'{ip}:{port}')
        self.ip = ip
        self.port = port
        self.ssh_user = ssh_user
        self.ssh_private_key = os.path.expanduser(ssh_private_key)
        self.ssh_proxy_command = ssh_proxy_command

    def _ssh_base(self) -> List[str]:
        opts = list(_DEFAULT_SSH_OPTIONS)
        opts += ['-o', 'ControlMaster=auto',
                 '-o', f'ControlPath={_control_path()}',
                 '-o', 'ControlPersist=120s']
        if self.ssh_proxy_command:
            opts += ['-o', f'ProxyCommand={self.ssh_proxy_command}']
        return ['ssh', *opts, '-i', self.ssh_private_key,
                '-p', str(self.port), f'{self.ssh_user}@{self.ip}']

    def run(self, cmd, *, require_outputs=False, stream_logs=False,
            log_path=None, env=None, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(cmd)
        if env:
            exports = ' && '.join(
                f'export {k}={shlex.quote(str(v))}' for k, v in env.items())
            cmd = f'{exports} && {cmd}'
        full = self._ssh_base() + [f'bash --login -c {shlex.quote(cmd)}']
        return self._exec(full, require_outputs=require_outputs,
                          stream_logs=stream_logs, log_path=log_path,
                          timeout=timeout)

    def interactive_shell_argv(self):
        # -tt forces a remote PTY even though our side is a PTY pair,
        # giving the user job control/sigwinch on the remote shell.
        return self._ssh_base() + ['-tt'], None, None

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        ssh_cmd = ' '.join(self._ssh_base()[:-1])
        rsync_cmd = ['rsync', '-az', '--delete-excluded']
        for pattern in excludes or []:
            rsync_cmd += ['--exclude', pattern]
        rsync_cmd += ['-e', ssh_cmd]
        remote = f'{self.ssh_user}@{self.ip}:{target}'
        if up:
            rsync_cmd += [source, remote]
        else:
            rsync_cmd += [remote, source]
        rc, out, _ = self._exec(rsync_cmd, require_outputs=True,
                                stream_logs=False, log_path=None,
                                timeout=600)
        if rc != 0:
            raise exceptions.CommandError(rc, ' '.join(rsync_cmd),
                                          f'rsync failed: {out[-2000:]}')


class LocalSandboxRunner(CommandRunner):
    """A "host" that is a local directory; commands run with HOME=dir.

    Backs the Local cloud: the full backend/agent/gang-exec path runs
    against these sandboxes with no cloud account (SURVEY §4's
    fake-cloud strategy, upgraded to real process execution).
    """

    def __init__(self, sandbox_dir: str) -> None:
        super().__init__(sandbox_dir)
        self.sandbox_dir = os.path.abspath(os.path.expanduser(sandbox_dir))
        os.makedirs(self.sandbox_dir, exist_ok=True)

    def _env(self, extra: Optional[Dict[str, str]]) -> Dict[str, str]:
        env = dict(os.environ)
        env['HOME'] = self.sandbox_dir
        if extra:
            env.update({k: str(v) for k, v in extra.items()})
        return env

    def run(self, cmd, *, require_outputs=False, stream_logs=False,
            log_path=None, env=None, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(cmd)
        full = ['bash', '-c', cmd]
        return self._exec(full, require_outputs=require_outputs,
                          stream_logs=stream_logs, log_path=log_path,
                          timeout=timeout, env=self._env(env),
                          cwd=self.sandbox_dir)

    def interactive_shell_argv(self):
        return ['bash', '-i'], self._env(None), self.sandbox_dir

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        if not up:
            source, target = target, source
        # Map absolute/remote-style paths into the sandbox.
        def into_sandbox(path: str) -> str:
            if path.startswith('~'):
                return os.path.join(self.sandbox_dir, path[1:].lstrip('/'))
            return path
        if up:
            target = into_sandbox(target)
        else:
            source = into_sandbox(source)
        cmd = ['rsync', '-az']
        for pattern in excludes or []:
            cmd += ['--exclude', pattern]
        cmd += [source, target]
        os.makedirs(os.path.dirname(target.rstrip('/')) or '.', exist_ok=True)
        rc, out, _ = self._exec(cmd, require_outputs=True, stream_logs=False,
                                log_path=None, timeout=600)
        if rc != 0:
            raise exceptions.CommandError(rc, ' '.join(cmd),
                                          f'rsync failed: {out[-2000:]}')
