"""Tiny sqlite helper: thread-local connections, dict rows, migrations.

The reference uses SQLAlchemy (sky/global_user_state.py); this build
uses stdlib sqlite3 with WAL mode — one writer, many readers — which
matches the single-API-server deployment model.
"""
from __future__ import annotations

import contextlib
import os
import sqlite3
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional


class SQLiteDB:

    def __init__(self, path: str, create_table_sql: str) -> None:
        self.path = os.path.expanduser(path)
        if self.path != ':memory:':
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._local = threading.local()
        self._create_sql = create_table_sql
        with self.conn() as conn:
            conn.executescript(create_table_sql)

    def _get_conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, 'conn', None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.row_factory = sqlite3.Row
            with contextlib.suppress(sqlite3.OperationalError):
                conn.execute('PRAGMA journal_mode=WAL')
            conn.execute('PRAGMA synchronous=NORMAL')
            self._local.conn = conn
        return conn

    @contextlib.contextmanager
    def conn(self) -> Iterator[sqlite3.Connection]:
        conn = self._get_conn()
        try:
            yield conn
            conn.commit()
        except Exception:
            conn.rollback()
            raise

    def execute(self, sql: str, params: tuple = ()) -> None:
        with self.conn() as conn:
            conn.execute(sql, params)

    def query(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        with self.conn() as conn:
            rows = conn.execute(sql, params).fetchall()
            return [dict(r) for r in rows]

    def query_one(self, sql: str,
                  params: tuple = ()) -> Optional[Dict[str, Any]]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def add_column_if_missing(self, table: str, column: str,
                              decl: str) -> None:
        with self.conn() as conn:
            cols = [r[1] for r in
                    conn.execute(f'PRAGMA table_info({table})').fetchall()]
            if column not in cols:
                conn.execute(f'ALTER TABLE {table} ADD COLUMN {column} {decl}')
