"""DB helpers: sqlite (default) or Postgres behind one interface.

The reference uses SQLAlchemy with a sqlite default and a Postgres
option for shared/HA API servers (sky/global_user_state.py:68-331).
Here the same dual-backend seam is stdlib-first: `SQLiteDB` (WAL mode
— one writer, many readers, matching the single-server deployment)
and `PostgresDB` (psycopg2/pg8000, selected by SKYPILOT_DB_URL) share
the execute/query/conn interface, with a small SQL translator mapping
the sqlite dialect the call sites speak (qmark params,
INSERT OR IGNORE/REPLACE, AUTOINCREMENT, BLOB) onto Postgres. Server
subsystems open their stores through `open_db`; on-cluster agent
state stays sqlite always.
"""
from __future__ import annotations

import contextlib
import os
import re
import sqlite3
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class SQLiteDB:

    def __init__(self, path: str, create_table_sql: str) -> None:
        self.path = os.path.expanduser(path)
        if self.path != ':memory:':
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._local = threading.local()
        self._create_sql = create_table_sql
        with self.conn() as conn:
            conn.executescript(create_table_sql)

    def _get_conn(self) -> sqlite3.Connection:
        # The pid guard makes cached connections fork-safe: a worker
        # process forked after the parent opened a connection must NOT
        # reuse the inherited handle (shared fd/socket corruption).
        conn = getattr(self._local, 'conn', None)
        if conn is None or getattr(self._local, 'pid', None) != os.getpid():
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.row_factory = sqlite3.Row
            with contextlib.suppress(sqlite3.OperationalError):
                conn.execute('PRAGMA journal_mode=WAL')
            conn.execute('PRAGMA synchronous=NORMAL')
            self._local.conn = conn
            self._local.pid = os.getpid()
        return conn

    @contextlib.contextmanager
    def conn(self) -> Iterator[sqlite3.Connection]:
        conn = self._get_conn()
        try:
            yield conn
            conn.commit()
        except Exception:
            conn.rollback()
            raise

    def execute(self, sql: str, params: tuple = ()) -> None:
        with self.conn() as conn:
            conn.execute(sql, params)

    def execute_rowcount(self, sql: str, params: tuple = ()) -> int:
        """Execute and return the affected-row count — the atomic
        claim primitive (UPDATE ... WHERE status='PENDING' wins on
        exactly one replica)."""
        with self.conn() as conn:
            return conn.execute(sql, params).rowcount

    def query(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        with self.conn() as conn:
            rows = conn.execute(sql, params).fetchall()
            return [dict(r) for r in rows]

    def query_one(self, sql: str,
                  params: tuple = ()) -> Optional[Dict[str, Any]]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def add_column_if_missing(self, table: str, column: str,
                              decl: str) -> None:
        with self.conn() as conn:
            cols = [r[1] for r in
                    conn.execute(f'PRAGMA table_info({table})').fetchall()]
            if column not in cols:
                conn.execute(f'ALTER TABLE {table} ADD COLUMN {column} {decl}')


# ---------------------------------------------------------------------------
# Postgres backend (reference: sky/global_user_state.py dual-backend).


def parse_schema(create_sql: str) -> Tuple[Dict[str, List[str]],
                                           Dict[str, str]]:
    """(primary_keys, autoinc_id_column) per table, parsed from the
    sqlite CREATE script — what the translator needs for
    INSERT OR REPLACE (conflict target) and lastrowid (RETURNING)."""
    pks: Dict[str, List[str]] = {}
    autoinc: Dict[str, str] = {}
    for m in re.finditer(
            r'CREATE TABLE IF NOT EXISTS\s+(\w+)\s*\((.*?)\);',
            create_sql, re.S | re.I):
        table, body = m.group(1), m.group(2)
        # Table-level composite key first (parens would confuse a
        # naive comma split), then column-level declarations per line.
        tm = re.search(r'PRIMARY KEY\s*\(([^)]+)\)', body, re.I)
        if tm:
            pks[table] = [c.strip() for c in tm.group(1).split(',')]
            continue
        for line in body.splitlines():
            line = line.strip().rstrip(',')
            cm = re.match(r'(\w+)\s+\w+.*PRIMARY KEY', line, re.I)
            if cm:
                pks[table] = [cm.group(1)]
                if 'AUTOINCREMENT' in line.upper():
                    autoinc[table] = cm.group(1)
                break
    return pks, autoinc


def translate_create_sql(create_sql: str) -> str:
    """sqlite CREATE script → Postgres dialect."""
    sql = re.sub(r'INTEGER PRIMARY KEY AUTOINCREMENT',
                 'BIGSERIAL PRIMARY KEY', create_sql, flags=re.I)
    sql = re.sub(r'\bBLOB\b', 'BYTEA', sql, flags=re.I)
    # sqlite REAL is 8-byte; Postgres REAL is float4, which quantizes
    # epoch timestamps to ~128s — FIFO ordering and retention math
    # would silently break.
    sql = re.sub(r'\bREAL\b', 'DOUBLE PRECISION', sql, flags=re.I)
    return sql


def translate_sql(sql: str, pks: Dict[str, List[str]]) -> str:
    """One sqlite-dialect statement → Postgres.

    Covers what the call sites actually use: qmark params,
    INSERT OR IGNORE, INSERT OR REPLACE (upsert via the table's
    primary key), and PRAGMA (dropped). None of our statements carry
    literal '?' in strings, so the param swap is a plain replace.
    """
    s = sql.strip()
    if s.upper().startswith('PRAGMA'):
        return ''
    m = re.match(r'INSERT OR IGNORE INTO\s+(.+)', s, re.I | re.S)
    if m:
        s = f'INSERT INTO {m.group(1)} ON CONFLICT DO NOTHING'
    m = re.match(r'INSERT OR REPLACE INTO\s+(\w+)\s*\(([^)]*)\)(.*)', s,
                 re.I | re.S)
    if m:
        table, cols_str, rest = m.groups()
        pk = pks.get(table)
        if pk is None:
            raise ValueError(
                f'INSERT OR REPLACE into {table!r} needs a PRIMARY KEY '
                f'for the Postgres upsert translation')
        cols = [c.strip() for c in cols_str.split(',')]
        updates = ', '.join(f'{c} = EXCLUDED.{c}' for c in cols
                            if c not in pk)
        s = (f'INSERT INTO {table} ({cols_str}){rest} '
             f'ON CONFLICT ({", ".join(pk)}) DO UPDATE SET {updates}')
    return s.replace('?', '%s')


class _PgCursor:
    """Minimal sqlite-cursor lookalike over a psycopg/pg8000 cursor."""

    def __init__(self, cur, lastrowid: Optional[int]) -> None:
        self._cur = cur
        self.lastrowid = lastrowid

    def fetchall(self):
        return self._cur.fetchall()

    def fetchone(self):
        return self._cur.fetchone()

    @property
    def description(self):
        return self._cur.description

    @property
    def rowcount(self):
        return self._cur.rowcount


class _PgConn:
    """Connection wrapper translating sqlite-dialect statements, so
    call sites using `with db.conn() as conn: conn.execute(...)` work
    unchanged against Postgres."""

    def __init__(self, raw, db: 'PostgresDB') -> None:
        self._raw = raw
        self._db = db

    def execute(self, sql: str, params: tuple = ()) -> _PgCursor:
        translated = translate_sql(sql, self._db.pks)
        cur = self._raw.cursor()
        if not translated:
            return _PgCursor(cur, None)
        lastrowid = None
        m = re.match(r'INSERT INTO\s+(\w+)', translated, re.I)
        if m and m.group(1) in self._db.autoinc and \
                'RETURNING' not in translated.upper():
            translated += f' RETURNING {self._db.autoinc[m.group(1)]}'
            cur.execute(translated, params)
            row = cur.fetchone()
            lastrowid = int(row[0]) if row else None
        else:
            cur.execute(translated, params)
        return _PgCursor(cur, lastrowid)

    def executescript(self, script: str) -> None:
        # Call sites run their own CREATE scripts through this
        # (pools, tokens): apply the DDL dialect mapping and absorb
        # any new tables' keys so later upserts translate too.
        script = translate_create_sql(script)
        new_pks, new_autoinc = parse_schema(script)
        self._db.pks.update(new_pks)
        self._db.autoinc.update(new_autoinc)
        for stmt in script.split(';'):
            if stmt.strip():
                self.execute(stmt)

    def commit(self) -> None:
        self._raw.commit()

    def rollback(self) -> None:
        self._raw.rollback()


class PostgresDB:
    """Same interface as SQLiteDB over a postgres:// URL.

    Reference: sky/global_user_state.py:68-331 — sqlite default with a
    Postgres option so several API-server replicas can share state.
    Driver: psycopg2 if importable, else pg8000 (both pure-API uses).
    """

    def __init__(self, url: str, create_table_sql: str) -> None:
        self.url = url
        self.pks, self.autoinc = parse_schema(create_table_sql)
        self._local = threading.local()
        self._migrated: set = set()
        self._create_sql = translate_create_sql(create_table_sql)
        with self.conn() as conn:
            conn.executescript(self._create_sql)

    @staticmethod
    def _connect(url: str):
        try:
            import psycopg2  # type: ignore
            return psycopg2.connect(url)
        except ImportError:
            pass
        try:
            import pg8000.dbapi  # type: ignore
            import urllib.parse as up
            parsed = up.urlparse(url)
            return pg8000.dbapi.Connection(
                user=parsed.username or 'postgres',
                password=parsed.password,
                host=parsed.hostname or 'localhost',
                port=parsed.port or 5432,
                database=(parsed.path or '/postgres').lstrip('/'))
        except ImportError as e:
            raise RuntimeError(
                'SKYPILOT_DB_URL points at Postgres but neither '
                'psycopg2 nor pg8000 is installed. `pip install '
                'psycopg2-binary` on the API server.') from e

    def _get_conn(self) -> _PgConn:
        # pid guard: a forked worker must open its OWN socket — parent
        # and child interleaving libpq bytes on one inherited socket
        # corrupts both sessions.
        conn = getattr(self._local, 'conn', None)
        if conn is None or getattr(self._local, 'pid', None) != os.getpid():
            conn = _PgConn(self._connect(self.url), self)
            self._local.conn = conn
            self._local.pid = os.getpid()
        return conn

    @contextlib.contextmanager
    def conn(self) -> Iterator[_PgConn]:
        conn = self._get_conn()
        try:
            yield conn
            conn.commit()
        except Exception:
            conn.rollback()
            raise

    def execute(self, sql: str, params: tuple = ()) -> None:
        with self.conn() as conn:
            conn.execute(sql, params)

    def execute_rowcount(self, sql: str, params: tuple = ()) -> int:
        with self.conn() as conn:
            return conn.execute(sql, params).rowcount

    def query(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        with self.conn() as conn:
            cur = conn.execute(sql, params)
            names = [d[0] for d in cur.description]
            return [dict(zip(names, row)) for row in cur.fetchall()]

    def query_one(self, sql: str,
                  params: tuple = ()) -> Optional[Dict[str, Any]]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def add_column_if_missing(self, table: str, column: str,
                              decl: str) -> None:
        # Memoized: hot paths call this per operation; on Postgres an
        # unconditional ALTER takes ACCESS EXCLUSIVE every time.
        key = (table, column)
        if key in self._migrated:
            return
        decl = translate_create_sql(decl)
        self.execute(
            f'ALTER TABLE {table} ADD COLUMN IF NOT EXISTS {column} {decl}')
        self._migrated.add(key)


def open_db(path: str, create_table_sql: str):
    """The dual-backend seam: SKYPILOT_DB_URL=postgres://... routes a
    server-side store to Postgres; default is sqlite at `path`."""
    url = os.environ.get('SKYPILOT_DB_URL')
    if url and url.startswith(('postgres://', 'postgresql://')):
        return PostgresDB(url, create_table_sql)
    return SQLiteDB(path, create_table_sql)


# ---------------------------------------------------------------------------
# Cross-replica advisory lock (multi-server leader election).


class AdvisoryLock:
    """Best-effort cross-replica mutex, for leader-electing singleton
    work (server maintenance daemons) across API-server replicas.

    Postgres (SKYPILOT_DB_URL set): `pg_try_advisory_lock` on a
    DEDICATED session — the lock lives exactly as long as this
    process's connection, so a crashed leader releases it
    automatically. sqlite deployments are single-host by construction
    (a shared sqlite file over the network is unsupported), so an
    exclusive flock on a sibling lockfile gives the same
    crash-release semantics between processes on that host.
    """

    def __init__(self, name: str, lock_dir: str) -> None:
        self.name = name
        self._lock_dir = lock_dir
        self._url = os.environ.get('SKYPILOT_DB_URL')
        self._pg_conn = None
        self._fd: Optional[int] = None
        self._held = False

    @property
    def held(self) -> bool:
        return self._held

    def _pg_key(self) -> int:
        import zlib
        return zlib.crc32(self.name.encode())  # stable bigint key

    def _pg_drop_conn(self) -> None:
        if self._pg_conn is not None:
            try:
                self._pg_conn.close()
            except Exception:  # pylint: disable=broad-except
                pass
            self._pg_conn = None
        self._held = False

    def try_acquire(self) -> bool:
        """Non-blocking; revalidated while held. Returns whether this
        process holds the lock RIGHT NOW. Never raises — a DB outage
        reads as not-leader (and drops the cached session so the next
        call reconnects); a dropped session also drops leadership,
        because Postgres released the server-side lock with it (a
        stale `held` here would mean two leaders)."""
        if self._url and self._url.startswith(('postgres://',
                                               'postgresql://')):
            if self._held:
                # The server-side lock lives exactly as long as the
                # session: probe it instead of trusting _held.
                try:
                    cur = self._pg_conn.cursor()
                    cur.execute('SELECT 1')
                    cur.fetchone()
                    self._pg_conn.commit()
                    return True
                except Exception:  # pylint: disable=broad-except
                    self._pg_drop_conn()
            try:
                if self._pg_conn is None:
                    self._pg_conn = PostgresDB._connect(self._url)
                cur = self._pg_conn.cursor()
                cur.execute('SELECT pg_try_advisory_lock(%s)',
                            (self._pg_key(),))
                self._held = bool(cur.fetchone()[0])
                self._pg_conn.commit()
            except Exception:  # pylint: disable=broad-except
                self._pg_drop_conn()
            return self._held
        if self._held:
            return True
        import fcntl
        os.makedirs(self._lock_dir, exist_ok=True)
        if self._fd is None:
            self._fd = os.open(
                os.path.join(self._lock_dir, f'{self.name}.lock'),
                os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            self._held = True
        except OSError:
            self._held = False
        return self._held

    def release(self) -> None:
        if not self._held:
            return
        if self._pg_conn is not None:
            try:
                cur = self._pg_conn.cursor()
                cur.execute('SELECT pg_advisory_unlock(%s)',
                            (self._pg_key(),))
                self._pg_conn.commit()
            except Exception:  # pylint: disable=broad-except
                self._pg_drop_conn()  # session death released it anyway
        elif self._fd is not None:
            import fcntl
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        self._held = False
