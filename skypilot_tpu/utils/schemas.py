"""JSON-schema validation for task YAML and config files.

Reference: sky/utils/schemas.py (2742 LoC of get_*_schema builders).
Role here: a friendly outer validation layer at the API boundary —
clear, path-annotated error messages before the strict Python parsers
(Task/Resources/ServiceSpec) run. The strict parsers remain the inner
source of truth; the schema catches shape errors (wrong types, unknown
fields, malformed nesting) with actionable hints.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

_RESOURCES_FIELDS: Dict[str, Any] = {
    'cloud': {'type': 'string'},
    'infra': {'type': 'string'},
    'region': {'type': 'string'},
    'zone': {'type': 'string'},
    'accelerators': {'type': ['string', 'object']},
    'accelerator_args': {'type': 'object'},
    'instance_type': {'type': 'string'},
    'cpus': {'type': ['string', 'number']},
    'memory': {'type': ['string', 'number']},
    'use_spot': {'type': 'boolean'},
    'disk_size': {'type': ['integer', 'string']},
    'ports': {'type': ['array', 'integer', 'string']},
    'labels': {'type': 'object'},
    'job_recovery': {'type': ['object', 'string']},
    'image_id': {'type': 'string'},
    'priority': {'type': ['integer', 'number']},
    'disk_tier': {'type': 'string'},
    'autostop': {'type': ['integer', 'boolean', 'object', 'string']},
    'config_overrides': {'type': 'object'},
}

_RESOURCES_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'properties': {
        **_RESOURCES_FIELDS,
        'any_of': {'type': 'array',
                   'items': {'type': 'object',
                             'properties': _RESOURCES_FIELDS,
                             'additionalProperties': False}},
        'ordered': {'type': 'array',
                    'items': {'type': 'object',
                              'properties': _RESOURCES_FIELDS,
                              'additionalProperties': False}},
    },
    'additionalProperties': False,
}

_SERVICE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'properties': {
        'readiness_probe': {'type': ['string', 'object']},
        'replicas': {'type': 'integer'},
        'replica_policy': {
            'type': 'object',
            'properties': {
                'min_replicas': {'type': 'integer', 'minimum': 0},
                'max_replicas': {'type': 'integer', 'minimum': 0},
                # Number (uniform fleet) or {accelerator: qps} map
                # (mixed fleet -> instance-aware autoscaler).
                'target_qps_per_replica': {
                    'anyOf': [
                        {'type': 'number', 'exclusiveMinimum': 0},
                        {'type': 'object', 'minProperties': 1,
                         'additionalProperties': {
                             'type': 'number', 'exclusiveMinimum': 0}},
                    ]},
                'upscale_delay_seconds': {'type': 'integer'},
                'downscale_delay_seconds': {'type': 'integer'},
                'base_ondemand_fallback_replicas': {'type': 'integer',
                                                    'minimum': 0},
                'dynamic_ondemand_fallback': {'type': 'boolean'},
                'target_queue_per_replica': {'type': 'number',
                                             'exclusiveMinimum': 0},
            },
            'additionalProperties': False,
        },
        'port': {'type': ['integer', 'string']},
        'load_balancing_policy': {'type': 'string'},
        'autoscaler': {'type': 'string'},
    },
    'additionalProperties': False,
}

TASK_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'properties': {
        'name': {'type': ['string', 'null']},
        'workdir': {'type': 'string'},
        'setup': {'type': 'string'},
        'run': {'type': ['string', 'null']},
        'num_nodes': {'type': 'integer', 'minimum': 1},
        'envs': {'type': 'object'},
        'secrets': {'type': 'object'},
        'file_mounts': {'type': 'object'},
        'volumes': {
            'type': 'object',
            'additionalProperties': {'type': 'string'},
        },
        'resources': _RESOURCES_SCHEMA,
        'service': _SERVICE_SCHEMA,
        'config': {'type': 'object'},
        'experimental': {'type': 'object'},
    },
    'additionalProperties': False,
}

CONFIG_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'properties': {
        'api_server': {'type': 'object'},
        'aws': {'type': 'object'},
        'azure': {'type': 'object'},
        'r2': {'type': 'object'},
        'gcp': {'type': 'object'},
        'kubernetes': {'type': 'object'},
        'ssh': {'type': 'object'},
        'jobs': {'type': 'object'},
        'serve': {'type': 'object'},
        'admin_policy': {'type': 'string'},
        'oauth': {'type': 'object'},
        'allowed_clouds': {'type': 'array', 'items': {'type': 'string'}},
        'workspaces': {'type': 'object'},
        'active_workspace': {'type': 'string'},
        'usage': {'type': 'object'},
        'logs': {'type': 'object'},
    },
    'additionalProperties': False,
}

# Common mistakes -> hints (reference: schemas.py error prettifiers).
_FIELD_HINTS = {
    'accelerator': "did you mean 'accelerators'?",
    'resource': "did you mean 'resources'?",
    'env': "did you mean 'envs'?",
    'mounts': "did you mean 'file_mounts'?",
    'node': "did you mean 'num_nodes'?",
    'nodes': "did you mean 'num_nodes'?",
}


def _format_error(err, what: str) -> str:
    path = '.'.join(str(p) for p in err.absolute_path) or '<top level>'
    msg = f'Invalid {what}: at `{path}`: {err.message}'
    if err.validator == 'additionalProperties':
        # Pull the offending key out of the message for a hint.
        import re
        m = re.search(r"'([^']+)' (?:was|were) unexpected", err.message)
        if m and m.group(1) in _FIELD_HINTS:
            msg += f' ({_FIELD_HINTS[m.group(1)]})'
    return msg


def _validate(config: Dict[str, Any], schema: Dict[str, Any],
              what: str) -> None:
    try:
        import jsonschema
    except ImportError:  # stripped-down image: strict parser still runs
        return
    validator = jsonschema.Draft7Validator(schema)
    errors = sorted(validator.iter_errors(config or {}),
                    key=lambda e: list(e.absolute_path))
    if errors:
        raise exceptions.InvalidTaskYAMLError(
            '\n'.join(_format_error(e, what) for e in errors[:5]))


def validate_task_config(config: Optional[Dict[str, Any]]) -> None:
    _validate(config or {}, TASK_SCHEMA, 'task YAML')


def validate_config(config: Optional[Dict[str, Any]]) -> None:
    _validate(config or {}, CONFIG_SCHEMA, 'config file')
