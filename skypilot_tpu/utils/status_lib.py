"""Status enums shared across layers.

Reference: sky/utils/status_lib.py (ClusterStatus, StatusVersion).
"""
from __future__ import annotations

import enum


class ClusterStatus(enum.Enum):
    """Status of a cluster, as recorded in global state."""
    INIT = 'INIT'          # provisioning, or in an inconsistent state
    UP = 'UP'              # all nodes up, runtime healthy
    STOPPED = 'STOPPED'    # nodes stopped (disks kept)

    def colored_str(self) -> str:
        color = {
            ClusterStatus.INIT: '\x1b[33m',     # yellow
            ClusterStatus.UP: '\x1b[32m',       # green
            ClusterStatus.STOPPED: '\x1b[90m',  # gray
        }[self]
        return f'{color}{self.value}\x1b[0m'


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'
    DELETED = 'DELETED'
