"""Canonical accelerator names.

Reference: sky/utils/accelerator_registry.py — canonicalizes user
accelerator strings and marks "schedulable non-GPU accelerators"
(TPUs) that must not be scheduled via GPU counts.
"""
from __future__ import annotations

from typing import Optional

from skypilot_tpu.utils import tpu_utils

# GPUs we keep in the catalog for cost comparison (GCP-first build).
_CANONICAL_GPUS = {
    'a100': 'A100',
    'a100-80gb': 'A100-80GB',
    'h100': 'H100',
    'h200': 'H200',
    'b200': 'B200',
    'l4': 'L4',
    't4': 'T4',
    'v100': 'V100',
    'p100': 'P100',
}


def canonicalize_accelerator_name(name: str) -> str:
    lower = name.lower()
    if tpu_utils.is_tpu(lower):
        # normalize e.g. TPU-V5P-128 -> tpu-v5p-128
        return lower
    if lower in _CANONICAL_GPUS:
        return _CANONICAL_GPUS[lower]
    return name


def is_schedulable_non_gpu_accelerator(name: Optional[str]) -> bool:
    """TPUs occupy whole hosts; never count them as GPUs for scheduling."""
    return tpu_utils.is_tpu(name)
