"""JAX version compatibility shims.

`shard_map` moved from `jax.experimental.shard_map` (<= 0.4.x, with a
`check_rep` flag and an `auto` axis set) to top-level `jax.shard_map`
(>= 0.5, `check_vma` flag and an `axis_names` manual-axis set). The
ops/parallel layers call this one wrapper with the NEW spelling and it
translates for whichever jax is installed — the container images pin
different jax versions per accelerator generation.
"""
from __future__ import annotations

from typing import Optional

import jax


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None,
              axis_names: Optional[set] = None):
    """`jax.shard_map` with graceful fallback to the experimental API.

    axis_names: the MANUAL mesh axes (new-API semantics); every other
    mesh axis stays auto/GSPMD-managed. None = all axes manual.
    """
    new_sm = getattr(jax, 'shard_map', None)
    if new_sm is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        if check_vma is not None:
            kwargs['check_vma'] = check_vma
        if axis_names is not None:
            kwargs['axis_names'] = set(axis_names)
        return new_sm(f, **kwargs)
    from jax.experimental.shard_map import shard_map as old_sm
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        kwargs['check_rep'] = check_vma
    if axis_names is not None:
        kwargs['auto'] = frozenset(mesh.axis_names) - set(axis_names)
    return old_sm(f, **kwargs)


def supports_partial_manual_axes() -> bool:
    """Whether shard_map can leave some mesh axes auto/GSPMD-managed
    (`axis_names` on new jax, `auto=` on old). Old XLA's SPMD
    partitioner rejects the PartitionId ops this produces
    ("PartitionId instruction is not supported for SPMD
    partitioning"), so partial-manual callers — pipeline-with-tensor-
    within-stages — must gate on this and fall back or skip."""
    return partial_manual_unsupported_reason() is None


_PM_REASON: Optional[list] = None


def partial_manual_unsupported_reason() -> Optional[str]:
    """None when partial-manual shard_map works on this jax/XLA, else
    the exact missing feature, probed (and cached) by compiling the
    failing ingredient: `lax.axis_index` over a manual axis while
    another mesh axis stays auto lowers to a PartitionId HLO that
    jax 0.4.x's bundled XLA SPMD partitioner rejects with
    "PartitionId instruction is not supported for SPMD partitioning".
    jax >= 0.5 (top-level `jax.shard_map`) ships an XLA that
    partitions it. The probe needs >= 4 devices (a 2x2 manual x auto
    mesh); with fewer it falls back to the version answer."""
    global _PM_REASON
    if _PM_REASON is not None:
        return _PM_REASON[0]
    if hasattr(jax, 'shard_map'):
        _PM_REASON = [None]
        return None
    devices = jax.devices()
    if len(devices) < 4:
        _PM_REASON = [
            'partial-manual shard_map needs jax >= 0.5 (top-level '
            'jax.shard_map); the jax 0.4.x experimental `auto=` path '
            'lowers axis_index to a PartitionId HLO its bundled XLA '
            'rejects under SPMD partitioning (probe skipped: < 4 '
            'devices)']
        return _PM_REASON[0]
    import numpy as np
    from jax import numpy as jnp
    from jax.experimental.shard_map import shard_map as old_sm
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.asarray(devices[:4]).reshape(2, 2),
                ('_pm_manual', '_pm_auto'))

    def probe(x):
        return x + jax.lax.axis_index('_pm_manual')

    try:
        fn = old_sm(probe, mesh=mesh, in_specs=P('_pm_manual'),
                    out_specs=P('_pm_manual'), check_rep=False,
                    auto=frozenset({'_pm_auto'}))
        jax.jit(fn)(jnp.arange(2, dtype=jnp.int32))
        _PM_REASON = [None]
    except Exception as e:  # pylint: disable=broad-except
        _PM_REASON = [f'{type(e).__name__}: {str(e).splitlines()[0]}']
    return _PM_REASON[0]


def axis_size(axis_name) -> 'jax.Array':
    """`lax.axis_size` (jax >= 0.5); psum(1) under a manual axis
    otherwise — same value, trace-time constant either way."""
    from jax import lax
    if hasattr(lax, 'axis_size'):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pvary(x, axis_names):
    """Mark `x` device-varying over `axis_names` (jax >= 0.7 vma
    tracking; >= 0.9 spells it pcast(to='varying')). A no-op on older
    jax, which has no varying-axes type system — callers run those
    shard_maps with check_vma=False."""
    from jax import lax
    if hasattr(lax, 'pcast'):
        return lax.pcast(x, axis_names, to='varying')
    if hasattr(lax, 'pvary'):
        return lax.pvary(x, axis_names)
    return x
