"""Adapter registry: hot-loadable multi-LoRA serving state.

One registry per serving process, shared by every engine in it. Two
halves under one lock:

  - INVENTORY: the artifact directory (`serve_lm --adapter-dir`) is
    scanned for `<name>/adapter_config.json` subdirectories (the
    `train_lm --lora` output format, models/lora.py). A lookup miss
    rescans, so dropping a new artifact into the directory makes it
    servable without a restart (hot-load). `gs://` dirs are synced
    to a local cache via gsutil once per (re)scan.
  - DEVICE STORE: `--max-adapters` stacked slots of A/B factors,
    `{'layer_i': {target: {'a': [N+1, d_in, R], 'b': [N+1, R,
    d_out]}}}` — row 0 is all-zeros (the base model), rows 1..N hold
    loaded adapters. The engine passes the WHOLE stack plus per-slot
    `adapter_ids` into its jitted decode/prefill fns; the model
    gathers each row's factors (models/lora.py `apply_delta`), so
    one dispatch serves many adapters. Loading writes one row
    in-place (donated `.at[slot].set`), never reshapes — no
    recompiles as adapters come and go.

Residency: `acquire()` pins (refcounts) an adapter while any engine
slot decodes with it; refcount-0 adapters stay resident (LRU) and
are evicted only when a load needs their device slot. A pinned
adapter is NEVER evicted — `acquire` returns None instead and the
engine re-queues the request (the same back-pressure contract as KV
page exhaustion). Artifacts with rank < the store rank are zero-
padded; `alpha/rank` is folded into the loaded B factors so the
engine always applies scale 1.

Chaos: the `adapters.load` fault point fires inside every artifact
load — a raised/dropped rule turns into AdapterLoadError (HTTP 503)
for that request only; the engine, the other adapters, and the base
model keep serving.
"""
from __future__ import annotations

import collections
import functools
import hashlib
import os
import subprocess
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu.inference import affinity
from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.observability import catalog as _obs
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (AdapterLoadError,
                                            AdapterNotFoundError)


_SET_ROW = None


def _write_rows(stack, row, idx):
    """One adapter's factors into stack row `idx`, in place (donated:
    XLA updates the resident buffers instead of copying the store).
    The jitted writer is cached module-wide so repeated hot-loads
    reuse one executable per stack geometry."""
    global _SET_ROW
    import jax
    import jax.numpy as jnp
    if _SET_ROW is None:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _set(stack, row, idx):
            return jax.tree.map(
                lambda s, r: s.at[idx].set(r.astype(s.dtype)),
                stack, row)

        _SET_ROW = _set
    return _SET_ROW(stack, row, jnp.asarray(idx, jnp.int32))


class AdapterRegistry:
    """Registry + device store. Thread-safe: engine scheduler threads
    acquire/release, HTTP threads read inventory/stats."""

    def __init__(self, adapter_dir: str, model, *,
                 max_adapters: int = 8, max_rank: int = 0,
                 mesh=None) -> None:
        if not lora_lib.supports(model):
            raise ValueError(
                f'{type(model).__name__} has no LoRA forward path; '
                f'multi-LoRA serving supports the Llama family '
                f'(models/lora.py)')
        if max_adapters < 1:
            raise ValueError(
                f'max_adapters must be >= 1, got {max_adapters}')
        self.model = model
        self.cfg = model.config
        self.max_adapters = int(max_adapters)
        # Tensor-parallel serving (--tensor N): the stacked factor
        # store is EXPLICITLY replicated over the mesh rather than
        # left to single-device default placement — the engine's
        # sharded dispatches then gather per-slot rows without a
        # cross-device fetch, and the donated row writes keep the
        # replicated layout. Factors are small (rank-r strips), so
        # replication costs ~nothing next to the sharded base.
        self._mesh = mesh
        self._dir = adapter_dir
        self._local_dir = adapter_dir  # set by _sync_remote for gs://
        self._lock = threading.Lock()
        # Inventory (disk): name -> adapter_config dict.
        self._inventory: Dict[str, Dict[str, Any]] = {}
        # Device store bookkeeping. Slots are 1-based (row 0 = base).
        self._loaded: Dict[str, int] = {}
        self._slot_name: Dict[int, str] = {}
        self._refs: Dict[int, int] = {}
        self._lru: 'collections.OrderedDict[str, None]' = \
            collections.OrderedDict()
        self._free: List[int] = list(range(self.max_adapters, 0, -1))
        self._stack = None           # built on first load
        self._model_lora = None
        self._rank = int(max_rank)   # 0 = fixed by the scanned max
        self._targets: Tuple[str, ...] = ()
        # Counters (mirrored as Prometheus series; see stats()).
        self.loads = 0
        self.evictions = 0
        self.load_failures = 0
        self.requests: Dict[str, int] = {}
        self.tokens: Dict[str, int] = {}
        self._m_loaded = _obs.gauge('skypilot_serving_adapters_loaded')
        self._m_load_failures = _obs.counter(
            'skypilot_serving_adapter_load_failures_total')
        with self._lock:
            self._scan_locked()

    # -- inventory ----------------------------------------------------------
    def _sync_remote_locked(self) -> None:
        """gs:// artifact dirs sync into a content-addressed local
        cache; local dirs are used as-is."""
        if not self._dir.startswith('gs://'):
            return
        cache = os.path.join(
            os.path.expanduser('~/.cache/skypilot_tpu/adapters'),
            hashlib.sha256(self._dir.encode()).hexdigest()[:16])
        os.makedirs(cache, exist_ok=True)
        try:
            subprocess.run(
                ['gsutil', '-m', 'rsync', '-r', self._dir, cache],
                check=True, capture_output=True, timeout=600)
        except (OSError, subprocess.SubprocessError) as e:
            raise AdapterLoadError(
                f'cannot sync adapter dir {self._dir}: '
                f'{type(e).__name__}: {e}') from e
        self._local_dir = cache

    def _scan_locked(self) -> None:
        self._sync_remote_locked()
        for name in lora_lib.list_adapter_dirs(self._local_dir):
            if name in self._inventory:
                continue
            try:
                config, _ = self._read_config(name)
            except (OSError, ValueError, KeyError):
                continue  # half-written artifact: picked up next scan
            self._inventory[name] = config
            if self._stack is None:
                # The store geometry is fixed by what the scan saw
                # before the first load (or --max-lora-rank).
                self._rank = max(self._rank, int(config['rank']))
                merged = dict.fromkeys(self._targets)
                merged.update(dict.fromkeys(config['targets']))
                self._targets = tuple(
                    t for t in lora_lib.ALL_TARGETS if t in merged)

    def _read_config(self, name: str) -> Tuple[Dict[str, Any], str]:
        path = os.path.join(self._local_dir, name)
        import json
        with open(os.path.join(path, lora_lib.CONFIG_FILE),
                  encoding='utf-8') as f:
            config = json.load(f)
        if 'rank' not in config or 'targets' not in config:
            raise ValueError(f'malformed adapter config for {name!r}')
        return config, path

    def inventory(self) -> List[str]:
        with self._lock:
            return sorted(self._inventory)

    def exists(self, name: str) -> bool:
        with self._lock:
            if name not in self._inventory:
                self._scan_locked()   # hot-load: new artifacts appear
            return name in self._inventory

    def resolve(self, name: str) -> None:
        """Raise AdapterNotFoundError unless `name` is servable."""
        if not self.exists(name):
            raise AdapterNotFoundError(
                f'adapter {name!r} not found in {self._dir} '
                f'(known: {self.inventory()})')

    def cache_salt(self, name: str) -> bytes:
        """Prefix-cache chain-key salt: KV pages are adapter-dependent
        once LoRA touches k/v projections, so the engine keys them per
        adapter (same constant the LB affinity keys use)."""
        return affinity.adapter_salt(name)

    # -- device store -------------------------------------------------------
    def _ensure_stack_locked(self) -> None:
        if self._stack is not None:
            return
        if self._rank < 1 or not self._targets:
            raise AdapterLoadError(
                'adapter store geometry unknown: no adapters scanned '
                'and no --max-lora-rank given')
        import jax
        import jax.numpy as jnp
        shapes = lora_lib.projection_shapes(self.cfg)
        n = self.max_adapters + 1
        stack: Dict[str, Any] = {}
        for i in range(self.cfg.num_layers):
            layer: Dict[str, Any] = {}
            for t in self._targets:
                d_in, d_out = shapes[t]
                layer[t] = {
                    'a': jnp.zeros((n, d_in, self._rank),
                                   self.cfg.dtype),
                    'b': jnp.zeros((n, self._rank, d_out),
                                   self.cfg.dtype),
                }
            stack[f'layer_{i}'] = layer
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(self._mesh, PartitionSpec())
            stack = jax.tree.map(
                lambda x: jax.device_put(x, replicated), stack)
        self._stack = stack
        self._refresh_model_lora_locked()

    def _refresh_model_lora_locked(self) -> None:
        import jax.numpy as jnp
        self._model_lora = {'scale': jnp.float32(1.0),
                            'layers': self._stack}

    def model_lora(self):
        """The pytree the engine passes into its jitted fns (scale is
        1.0: per-adapter alpha/rank is folded into B at load)."""
        with self._lock:
            return self._model_lora

    def _load_locked(self, name: str, slot: int) -> None:
        """Read the artifact and write stack row `slot`. Any failure
        (including an injected `adapters.load` fault) surfaces as
        AdapterLoadError without touching the other rows."""
        try:
            if faults.point('adapters.load', adapter=name) is \
                    faults.DROP:
                raise AdapterLoadError(
                    f'injected adapters.load drop for {name!r}')
            config, path = self._read_config(name)
            spec = lora_lib.load_spec(config)
            self._ensure_stack_locked()
            if spec.rank > self._rank:
                raise AdapterLoadError(
                    f'adapter {name!r} has rank {spec.rank} > store '
                    f'rank {self._rank}; restart with --max-lora-rank '
                    f'{spec.rank}')
            missing = [t for t in spec.targets
                       if t not in self._targets]
            if missing:
                raise AdapterLoadError(
                    f'adapter {name!r} adapts {missing}, not in the '
                    f'store target set {list(self._targets)} (fixed '
                    f'at startup); restart to widen it')
            _, weights = lora_lib.load_adapter(path)
            shapes = lora_lib.projection_shapes(self.cfg)
            row: Dict[str, Any] = {}
            for i in range(self.cfg.num_layers):
                lname = f'layer_{i}'
                layer: Dict[str, Any] = {}
                for t in self._targets:
                    d_in, d_out = shapes[t]
                    factors = weights.get(lname, {}).get(t)
                    a = np.zeros((d_in, self._rank), np.float32)
                    b = np.zeros((self._rank, d_out), np.float32)
                    if factors is not None:
                        fa = np.asarray(factors['a'], np.float32)
                        fb = np.asarray(factors['b'], np.float32)
                        if fa.shape != (d_in, spec.rank) or \
                                fb.shape != (spec.rank, d_out):
                            raise AdapterLoadError(
                                f'adapter {name!r} {lname}/{t} shape '
                                f'{fa.shape}x{fb.shape} does not '
                                f'match the serving model '
                                f'({d_in},{spec.rank})x'
                                f'({spec.rank},{d_out})')
                        a[:, :spec.rank] = fa
                        # alpha/rank folds into B: the engine applies
                        # scale 1 for every adapter in the stack.
                        b[:spec.rank, :] = fb * spec.scale
                    layer[t] = {'a': a, 'b': b}
                row[lname] = layer
            self._stack = _write_rows(self._stack, row, slot)
            self._refresh_model_lora_locked()
        except AdapterLoadError:
            self.load_failures += 1
            self._m_load_failures.inc()
            raise
        except Exception as e:
            self.load_failures += 1
            self._m_load_failures.inc()
            raise AdapterLoadError(
                f'loading adapter {name!r} failed: '
                f'{type(e).__name__}: {e}') from e
        self.loads += 1
        _obs.counter(
            'skypilot_serving_adapter_loads_total').labels(
                adapter=name).inc()

    def acquire(self, name: str) -> Optional[int]:
        """Pin `name` and return its device slot id (1-based; 0 is
        the base model and never returned). Loads — evicting the LRU
        unpinned adapter if the store is full — when not resident.
        Returns None when every slot is pinned by a running request
        (the caller re-queues); raises AdapterNotFoundError /
        AdapterLoadError for missing / unloadable artifacts."""
        with self._lock:
            if name not in self._inventory:
                self._scan_locked()
            if name not in self._inventory:
                raise AdapterNotFoundError(
                    f'adapter {name!r} not found in {self._dir} '
                    f'(known: {sorted(self._inventory)})')
            slot = self._loaded.get(name)
            if slot is not None:
                self._refs[slot] = self._refs.get(slot, 0) + 1
                self._lru.pop(name, None)
                self._count_request_locked(name)
                return slot
            if not self._free:
                if not self._lru:
                    return None   # every slot pinned: back-pressure
                evictee, _ = self._lru.popitem(last=False)
                freed = self._loaded.pop(evictee)
                del self._slot_name[freed]
                self._free.append(freed)
                self.evictions += 1
                _obs.counter(
                    'skypilot_serving_adapter_evictions_total').labels(
                        adapter=evictee).inc()
            slot = self._free[-1]
            self._load_locked(name, slot)   # raises on failure
            self._free.pop()
            self._loaded[name] = slot
            self._slot_name[slot] = name
            self._refs[slot] = 1
            self._count_request_locked(name)
            self._m_loaded.set(len(self._loaded))
            return slot

    def release(self, slot: int, tokens: int = 0) -> None:
        """Unpin one acquire(); refcount 0 makes the adapter LRU-
        evictable (it stays resident until a load needs the slot).
        `tokens` adds the request's committed tokens to the
        per-adapter counter."""
        with self._lock:
            name = self._slot_name.get(slot)
            if name is None:
                return
            self._refs[slot] = self._refs.get(slot, 1) - 1
            if self._refs[slot] <= 0:
                self._refs.pop(slot, None)
                self._lru[name] = None
            if tokens > 0:
                self.tokens[name] = self.tokens.get(name, 0) + tokens
                _obs.counter(
                    'skypilot_serving_adapter_tokens_total').labels(
                        adapter=name).inc(tokens)

    def _count_request_locked(self, name: str) -> None:
        self.requests[name] = self.requests.get(name, 0) + 1
        _obs.counter(
            'skypilot_serving_adapter_requests_total').labels(
                adapter=name).inc()

    # -- observability ------------------------------------------------------
    def loaded_names(self) -> List[str]:
        with self._lock:
            return sorted(self._loaded)

    def stats(self) -> Dict[str, Any]:
        """The `/stats` adapters section (also scraped into the
        replica plane's /fleet/status views)."""
        with self._lock:
            bytes_per = (lora_lib.adapter_num_bytes(
                self.cfg, self._rank,
                self._targets or lora_lib.ATTN_TARGETS,
                bytes_per_elem=np.dtype(self.cfg.dtype).itemsize)
                if self._rank else 0)
            return {
                'inventory': sorted(self._inventory),
                'loaded': sorted(self._loaded),
                'pinned': sorted(self._slot_name[s]
                                 for s, r in self._refs.items()
                                 if r > 0),
                'max_adapters': self.max_adapters,
                'rank': self._rank,
                'targets': list(self._targets),
                'loads': self.loads,
                'evictions': self.evictions,
                'load_failures': self.load_failures,
                'requests': dict(self.requests),
                'tokens': dict(self.tokens),
                'bytes_per_adapter': bytes_per,
                'device_bytes': bytes_per * len(self._loaded),
            }
