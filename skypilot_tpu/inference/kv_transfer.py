"""KV-page wire format + tiered prefix-cache spill storage.

Two consumers, one page encoding:

  - DISAGGREGATED PREFILL/DECODE HANDOFF: a prefill replica finishes
    a prompt, exports the prompt's full-page KV chain from its prefix
    cache (`ContinuousBatchingEngine.export_chain`) and POSTs the
    packed bytes to the assigned decode replica's `/kv/import`, which
    scatters them into its own page pool and admits the request with
    the prompt's pages already resident — decode never pays the
    compute-bound prefill (only the sub-page prompt tail, < one page,
    is recomputed locally, which is what keeps the existing
    at-least-one-token admission contract intact).
  - TIERED PREFIX CACHE: pages the cache would drop under pool
    pressure (`PrefixCache.evict_into`) spill — payload + scales +
    chain key — into a bounded host-RAM LRU (`HostSpillTier`), with
    an optional cold tier (`ColdTier`: a local directory or gs://
    prefix) behind it; a later chain-key hit restores the exact bytes
    instead of recomputing the prefill.

The encoding is FORMAT-BLIND by construction: it serializes whatever
leaves the paged cache holds — bf16 (or f32) k/v page arrays, or
int8 pages plus their parallel f32 scale arrays — as raw bytes with
dtype/shape metadata. int8 pages travel as int8 (no dequantize on
the wire), so export -> import round trips are bit-identical and a
restored page is indistinguishable from a freshly computed one.

Wire layout: MAGIC ++ u64 header length ++ header JSON ++ payload.
The header carries the chain keys (hex), the adapter salt, the page
geometry (kv_dtype, page_size, and — since PR 15 — num_kv_heads /
head_dim) and one (path, dtype, shape) record per cache leaf; the
payload is each leaf's page-major array bytes in header order.
Everything is numpy + stdlib — the packing side runs on the engine
scheduler thread, the unpacking side may run anywhere.

MESH-AGNOSTIC BY CONSTRUCTION: exported blobs hold GLOBAL page rows
— the engine's gather device_gets the sharded pool, which assembles
the kv-head shards — so a chain exported from a tensor-N prefill
mesh imports into a decode mesh of any size; the importer's own
cache shardings re-scatter on write. The header geometry lets the
importer reject a genuinely different model loudly instead of
scattering garbage.
"""
from __future__ import annotations

import collections
import json
import os
import subprocess
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu.utils import ux_utils

#: Wire magic + version. Bump on any layout change: an importer must
#: never guess at bytes from a different build.
MAGIC = b'STPUKV1\n'


def _dtype_of(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes extended set
    (bfloat16 is the serving default page dtype)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_pages(blobs: Dict[str, np.ndarray],
               meta: Dict[str, Any]) -> bytes:
    """Serialize page-major per-leaf arrays + metadata. `blobs` maps
    a cache leaf path to an array whose LEADING axis is the page
    index (gather_page_rows layout); every leaf must agree on the
    page count. `meta` must already carry kv_dtype/page_size/keys/
    salt — this function only adds the leaf table."""
    leaves = []
    payload = []
    n_pages = None
    for path in sorted(blobs):
        arr = np.ascontiguousarray(blobs[path])
        if n_pages is None:
            n_pages = arr.shape[0]
        elif arr.shape[0] != n_pages:
            raise ValueError(
                f'leaf {path} has {arr.shape[0]} pages, expected '
                f'{n_pages} (all leaves must cover the same chain)')
        leaves.append({'path': path, 'dtype': arr.dtype.name,
                       'shape': list(arr.shape)})
        payload.append(arr.tobytes())
    header = dict(meta)
    header['version'] = 1
    header['n_pages'] = int(n_pages or 0)
    header['leaves'] = leaves
    hjson = json.dumps(header, sort_keys=True).encode()
    return (MAGIC + len(hjson).to_bytes(8, 'big') + hjson +
            b''.join(payload))


def unpack_pages(data: bytes
                 ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Inverse of `pack_pages`. Raises ValueError on anything that is
    not a well-formed chain of the advertised geometry — the caller
    (HTTP import, cold-tier read) treats that as a failed transfer
    and falls back, never as a crash."""
    if not data.startswith(MAGIC):
        raise ValueError('not a KV page chain (bad magic)')
    off = len(MAGIC)
    hlen = int.from_bytes(data[off:off + 8], 'big')
    off += 8
    try:
        meta = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise ValueError(f'corrupt KV chain header: {e}') from e
    off += hlen
    if meta.get('version') != 1:
        raise ValueError(
            f'unsupported KV chain version {meta.get("version")!r}')
    blobs: Dict[str, np.ndarray] = {}
    for leaf in meta.get('leaves', []):
        dtype = _dtype_of(leaf['dtype'])
        shape = tuple(int(s) for s in leaf['shape'])
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        raw = data[off:off + nbytes]
        if len(raw) != nbytes:
            raise ValueError(
                f'truncated KV chain payload at leaf {leaf["path"]}')
        blobs[leaf['path']] = np.frombuffer(
            raw, dtype=dtype).reshape(shape)
        off += nbytes
    if off != len(data):
        raise ValueError(
            f'{len(data) - off} trailing bytes after KV chain payload')
    return meta, blobs


def split_pages(blobs: Dict[str, np.ndarray], n_pages: int
                ) -> List[Dict[str, np.ndarray]]:
    """Page-major chain arrays -> one {path: row} blob per page (the
    spill tier's unit)."""
    return [{path: arr[i] for path, arr in blobs.items()}
            for i in range(n_pages)]


def join_pages(page_blobs: List[Dict[str, np.ndarray]]
               ) -> Dict[str, np.ndarray]:
    """Inverse of `split_pages`: stack per-page blobs back into the
    page-major chain layout (restore/import scatter input)."""
    assert page_blobs
    return {path: np.stack([blob[path] for blob in page_blobs])
            for path in page_blobs[0]}


def page_blob_nbytes(blob: Dict[str, np.ndarray]) -> int:
    return int(sum(arr.nbytes for arr in blob.values()))


class ColdTier:
    """Content-addressed page blobs in a directory — the cache's
    coldest tier, for giant shared system prompts that should survive
    process restarts (and, under the crash-only controller, replica
    replacement). `root` is a local directory or a gs:// prefix
    (gs:// objects move via gsutil; failures are logged and the page
    is simply treated as not-cold-cached — the tier is an
    optimization, never a correctness dependency)."""

    def __init__(self, root: str) -> None:
        self.root = root.rstrip('/')
        self.is_gs = self.root.startswith('gs://')
        if not self.is_gs:
            os.makedirs(self.root, exist_ok=True)
        self.writes = 0
        self.reads = 0
        self.errors = 0

    def _path(self, key: bytes) -> str:
        return f'{self.root}/{key.hex()}.kvpage'

    def put(self, key: bytes, blob: Dict[str, np.ndarray]) -> None:
        data = pack_pages(join_pages([blob]), {'kind': 'cold_page'})
        try:
            if self.is_gs:
                with tempfile.NamedTemporaryFile(delete=False) as f:
                    f.write(data)
                    tmp = f.name
                try:
                    subprocess.run(['gsutil', '-q', 'cp', tmp,
                                    self._path(key)], check=True,
                                   capture_output=True)
                finally:
                    os.unlink(tmp)
            else:
                tmp = f'{self._path(key)}.tmp.{os.getpid()}'
                with open(tmp, 'wb') as f:
                    f.write(data)
                os.replace(tmp, self._path(key))
            self.writes += 1
        except (OSError, subprocess.SubprocessError) as e:
            self.errors += 1
            ux_utils.log(f'kv cold tier: write of page '
                         f'{key.hex()[:12]} failed ({e}); dropping.')

    def get(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        try:
            if self.is_gs:
                with tempfile.NamedTemporaryFile(delete=False) as f:
                    tmp = f.name
                try:
                    subprocess.run(['gsutil', '-q', 'cp',
                                    self._path(key), tmp], check=True,
                                   capture_output=True)
                    with open(tmp, 'rb') as f:
                        data = f.read()
                finally:
                    os.unlink(tmp)
            else:
                try:
                    with open(self._path(key), 'rb') as f:
                        data = f.read()
                except FileNotFoundError:
                    return None
            _meta, blobs = unpack_pages(data)
            self.reads += 1
            return split_pages(blobs, 1)[0]
        except (OSError, ValueError, subprocess.SubprocessError) as e:
            self.errors += 1
            ux_utils.log(f'kv cold tier: read of page '
                         f'{key.hex()[:12]} failed ({e}); treating as '
                         f'a miss.')
            return None

    def stats(self) -> Dict[str, Any]:
        return {'root': self.root, 'writes': self.writes,
                'reads': self.reads, 'errors': self.errors}


class HostSpillTier:
    """Bounded host-RAM LRU of evicted prefix-cache pages, keyed by
    chain key. `put` is called by `PrefixCache.evict_into` on the
    engine scheduler thread with the page's exact device bytes; `get`
    restores them on a later chain hit (restore == fresh compute,
    bit-identical). Pages LRU-evicted from host RAM fall through to
    the cold tier when one is configured, otherwise they are dropped
    (back to the pre-tier recompute behavior).

    Thread-safe: puts/gets run on the scheduler thread, but /stats
    scrapes the counters from HTTP threads."""

    def __init__(self, capacity_bytes: int,
                 cold: Optional[ColdTier] = None) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self.cold = cold
        self._lock = threading.Lock()
        self._pages: 'collections.OrderedDict[bytes, Dict[str, np.ndarray]]' = \
            collections.OrderedDict()
        self.bytes = 0
        self.spilled_pages = 0      # puts (from evictions)
        self.restored_pages = 0     # gets that hit (host or cold)
        self.cold_demotions = 0     # host LRU -> cold tier
        self.dropped_pages = 0      # host LRU -> nowhere
        self.lookups = 0
        self.hits = 0

    def put(self, key: bytes, blob: Dict[str, np.ndarray]) -> None:
        nbytes = page_blob_nbytes(blob)
        with self._lock:
            old = self._pages.pop(key, None)
            if old is not None:
                self.bytes -= page_blob_nbytes(old)
            self._pages[key] = blob
            self.bytes += nbytes
            self.spilled_pages += 1
            demote = []
            while self.bytes > self.capacity_bytes and \
                    len(self._pages) > 1:
                victim_key, victim = self._pages.popitem(last=False)
                self.bytes -= page_blob_nbytes(victim)
                demote.append((victim_key, victim))
        for victim_key, victim in demote:
            if self.cold is not None:
                self.cold_demotions += 1
                self.cold.put(victim_key, victim)
            else:
                self.dropped_pages += 1

    def get(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            self.lookups += 1
            blob = self._pages.get(key)
            if blob is not None:
                self._pages.move_to_end(key)
                self.hits += 1
                self.restored_pages += 1
                return blob
        if self.cold is None:
            return None
        blob = self.cold.get(key)
        if blob is None:
            return None
        with self._lock:
            self.hits += 1
            self.restored_pages += 1
        # Promote back to the host tier (it is hot again).
        self.put(key, blob)
        with self._lock:
            self.spilled_pages -= 1  # the promotion is not a spill
        return blob

    def resident_pages(self) -> int:
        with self._lock:
            return len(self._pages)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            body = {
                'capacity_bytes': self.capacity_bytes,
                'bytes': self.bytes,
                'resident_pages': len(self._pages),
                'spilled_pages': self.spilled_pages,
                'restored_pages': self.restored_pages,
                'lookups': self.lookups,
                'hits': self.hits,
                'hit_rate': round(self.hits / max(self.lookups, 1), 4),
                'cold_demotions': self.cold_demotions,
                'dropped_pages': self.dropped_pages,
            }
        if self.cold is not None:
            body['cold'] = self.cold.stats()
        return body


def make_spill_tier(spill_bytes: int,
                    cold_dir: Optional[str] = None
                    ) -> Optional[HostSpillTier]:
    """The serve_lm --kv-spill-bytes/--kv-cold-dir wiring: a cold dir
    without a host budget still gets a small host tier in front (the
    cold tier alone would make every restore a file read)."""
    if not spill_bytes and not cold_dir:
        return None
    cold = ColdTier(cold_dir) if cold_dir else None
    return HostSpillTier(spill_bytes or 64 * 1024 * 1024, cold=cold)
