"""Routing-affinity keys for the multi-replica LB.

The prefix cache (models/batching.py PrefixCache) keys KV pages by a
chain hash: key_i = sha256 of ALL prompt tokens through full page i.
Two requests sharing a system prompt therefore share their leading
chain keys — and the replica that served one of them already holds
those KV pages. The replica-plane load balancer hashes the FIRST
full-page chain key into its consistent-hash ring so such requests
land on the same replica (serve/load_balancing_policies.py
PrefixAffinityPolicy).

This module re-derives the chain hash with numpy + hashlib only — an
LB process must not pay a JAX import to route a request. Parity with
`PrefixCache.chain_keys` is pinned by a unit test; if the page-hash
scheme ever changes there, change it here too.

Text endpoints (/generate_text, /v1/*) have no token ids at the LB
(tokenization happens on the replica), so their key is a hash of the
leading characters — an approximation of "same system prompt" that
is exact for the dominant case (identical template prefixes).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

import numpy as np

#: Must match the engine's KV page size (models/batching.py default).
DEFAULT_PAGE_SIZE = 16

#: Leading characters hashed for text-prompt affinity. Long enough to
#: span a realistic system prompt's distinctive part, short enough
#: that per-user suffixes (appended after the template) don't split
#: the group.
TEXT_PREFIX_CHARS = 256


def adapter_salt(model: Optional[str]) -> bytes:
    """Chain-key salt for a LoRA adapter request. KV pages are
    adapter-dependent once LoRA touches the k/v projections, so both
    the engine's PrefixCache keys AND the LB affinity keys fold the
    adapter identity in — same prompt under two adapters must never
    share pages (tenant isolation) or an affinity group. Empty salt
    (base model) keeps keys byte-identical to the pre-LoRA scheme."""
    if not model:
        return b''
    return b'lora\x00' + str(model).encode('utf-8', 'replace')


def chain_keys(tokens: List[int], page_size: int,
               salt: bytes = b'') -> List[bytes]:
    """One key per FULL page; identical to
    models/batching.PrefixCache.chain_keys (parity-tested) without
    importing the engine (and its JAX dependency). `salt` prefixes
    the hash chain (adapter identity)."""
    keys = []
    h = hashlib.sha256()
    if salt:
        h.update(salt)
    for i in range(len(tokens) // page_size):
        chunk = tokens[i * page_size:(i + 1) * page_size]
        h.update(np.asarray(chunk, np.int32).tobytes())
        keys.append(h.digest())
    return keys


def token_affinity_key(tokens: List[int],
                       page_size: int = DEFAULT_PAGE_SIZE,
                       salt: bytes = b'') -> Optional[str]:
    """Affinity key for a token prompt: the FIRST full-page chain key
    (hex). The first page commits to the first `page_size` tokens —
    the shared-system-prompt signature — while later keys diverge as
    soon as user content does. Prompts shorter than one page have no
    cacheable full page, hence no key (caller falls back to
    least-load)."""
    keys = chain_keys(tokens, page_size, salt=salt)
    if not keys:
        return None
    return keys[0].hex()


def text_affinity_key(text: str, salt: bytes = b'') -> Optional[str]:
    if not text:
        return None
    return hashlib.sha256(
        salt + text[:TEXT_PREFIX_CHARS].encode('utf-8',
                                               'replace')).hexdigest()


def request_affinity_key(path: str, body: Dict[str, Any],
                         page_size: int = DEFAULT_PAGE_SIZE
                         ) -> Optional[str]:
    """Extract the routing key from a generation request body, by
    endpoint shape. The body's `model` field (adapter selection)
    salts the key, so a tenant's requests pin to the replica holding
    both its KV pages AND its hot-loaded adapter — and never share an
    affinity group with another tenant's identical prompt. Returns
    None for anything unrecognized — the LB then routes by load,
    never errors."""
    try:
        salt = adapter_salt(body.get('model'))
        if path in ('/generate', '/v1/generate'):
            tokens = body.get('tokens') or []
            if tokens and isinstance(tokens[0], list):
                tokens = tokens[0]
            return token_affinity_key([int(t) for t in tokens],
                                      page_size, salt=salt)
        if path in ('/generate_text', '/v1/generate_text'):
            prompts = body.get('prompts', '')
            if isinstance(prompts, list):
                prompts = prompts[0] if prompts else ''
            return text_affinity_key(str(prompts), salt=salt)
        if path == '/v1/completions':
            prompt = body.get('prompt', '')
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ''
            return text_affinity_key(str(prompt), salt=salt)
        if path == '/v1/chat/completions':
            messages = body.get('messages') or []
            # The system message IS the shared prefix; chats without
            # one key on their first message (session affinity).
            for message in messages:
                if message.get('role') == 'system':
                    return text_affinity_key(
                        str(message.get('content', '')), salt=salt)
            if messages:
                return text_affinity_key(
                    str(messages[0].get('content', '')), salt=salt)
    except (TypeError, ValueError, KeyError, IndexError):
        # Malformed bodies are the replica's 400 to give, not the
        # LB's 500: route keyless.
        return None
    return None
