"""OpenAI-compatible completions/chat shims + SSE streaming.

The de-facto client contract: the reference's llm/ recipes serve vLLM
(/root/reference/llm/vllm/README.md:74,159 drives /v1/completions and
/v1/chat/completions), whose clients stream by default. Implements:

  - non-streaming completions with `n >= 1` (one-shot path batches
    the n samples into a single [n, P] generate call; the continuous
    engine fans out n slot submissions that decode concurrently);
  - SSE streaming (`stream: true`) with the OpenAI chunk schemas
    (`text_completion` chunks; `chat.completion.chunk` deltas), tokens
    flushed as the engine commits them;
  - incremental detokenization (UTF-8-safe: a token ending in a
    partial multi-byte sequence is held until complete);
  - stop-string scanning with holdback (text that could be the prefix
    of a stop string is not emitted until disambiguated).

Requests are executed through `InferenceRuntime`; HTTP writing goes
through the handler's small writer surface (send_json / sse_*).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.inference.runtime import (InferenceRuntime,
                                            iter_interleaved)


class IncrementalDecoder:
    """Streamed token ids -> text deltas.

    Decodes the full generated-id prefix each push (O(n) per token —
    fine at serving lengths; HF's streamer uses the same shape) and
    emits only the new suffix. A trailing U+FFFD means the byte-level
    BPE stream ends mid-codepoint: hold until the next token completes
    it."""

    def __init__(self, tok) -> None:
        self.tok = tok
        self.ids: List[int] = []
        self.text = ''

    def push(self, tok_id: int) -> str:
        self.ids.append(tok_id)
        full = self.tok.decode(self.ids, skip_special_tokens=True)
        if full.endswith('�'):
            return ''
        delta = full[len(self.text):]
        self.text = full
        return delta

    def flush(self) -> str:
        """Final delta (drops an unresolved partial codepoint)."""
        full = self.tok.decode(self.ids, skip_special_tokens=True)
        if full.endswith('�'):
            full = full[:-1]
        delta = full[len(self.text):]
        self.text = full
        return delta


class StopStringScanner:
    """Emit-safe streaming with OpenAI `stop` semantics: the completion
    ends BEFORE the first occurrence of any stop string, and no text
    that might turn out to be part of one is ever emitted early."""

    def __init__(self, stops: List[str]) -> None:
        self.stops = [s for s in stops if s]
        self.buf = ''
        self.emitted = 0
        self.hit = False

    def _holdback(self) -> int:
        """Length of the longest buffer suffix that is a proper prefix
        of some stop string (must not be emitted yet)."""
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self.buf)), 0, -1):
                if self.buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        return hold

    def push(self, delta: str) -> str:
        """Returns the newly emittable text; sets `hit` when a stop
        string landed (emittable text ends right before it)."""
        if self.hit:
            return ''
        self.buf += delta
        cut = -1
        for s in self.stops:
            i = self.buf.find(s)
            if i != -1:
                cut = i if cut == -1 else min(cut, i)
        if cut != -1:
            self.hit = True
            out = self.buf[self.emitted:cut]
            self.emitted = cut
            return out
        safe = len(self.buf) - self._holdback()
        out = self.buf[self.emitted:safe]
        self.emitted = max(self.emitted, safe)
        return out

    def flush(self) -> str:
        if self.hit:
            return ''
        out = self.buf[self.emitted:]
        self.emitted = len(self.buf)
        return out


def trim_stops(text: str, stops: List[str]) -> Tuple[str, bool]:
    cut = -1
    for s in stops:
        if not s:
            continue
        i = text.find(s)
        if i != -1:
            cut = i if cut == -1 else min(cut, i)
    if cut != -1:
        return text[:cut], True
    return text, False


class CompletionRequest:
    """Validated, normalized body shared by both /v1 endpoints."""

    def __init__(self, prompts: List[str], max_new: int,
                 temperature: float, top_p: float,
                 stop_strings, n: int, stream: bool,
                 logprobs: Optional[int] = None,
                 echo: bool = False,
                 deadline_s: float = 600.0,
                 adapter: Optional[str] = None,
                 model: Optional[str] = None) -> None:
        if isinstance(stop_strings, str):
            stop_strings = [stop_strings]
        if logprobs is not None and adapter is not None:
            raise ValueError(
                'logprobs with an adapter model is not supported '
                '(the scoring pass runs base weights)')
        if n < 1 or n > 16:
            raise ValueError(f'n must be in [1, 16], got {n}')
        if stream and len(prompts) != 1:
            raise ValueError(
                'stream=true supports a single prompt per request')
        if logprobs is not None:
            logprobs = int(logprobs)
            if not 0 <= logprobs <= 5:
                raise ValueError(
                    f'logprobs must be in [0, 5], got {logprobs}')
            if stream:
                raise ValueError(
                    'logprobs with stream=true is not supported')
        if echo and logprobs is None:
            raise ValueError('echo requires logprobs')
        self.prompts = prompts
        self.max_new = max_new
        self.temperature = temperature
        self.top_p = top_p
        self.stop_strings = list(stop_strings or [])
        self.n = n
        self.stream = stream
        self.logprobs = logprobs
        self.echo = echo
        # Per-request deadline, seconds (the server clamps the body's
        # `timeout` field into (0, --request-timeout]); propagated to
        # engine slots so an expired request is reaped mid-decode.
        self.deadline_s = float(deadline_s)
        # `model` field: the resolved adapter (None = base) and the
        # name to echo in responses (the OpenAI contract reports the
        # REQUESTED model, not always the base).
        self.adapter = adapter
        self.model = model


def _logprobs_block(rt: InferenceRuntime, tok, row: List[int],
                    n_top: int, echo: bool,
                    prompt_len: int) -> Dict[str, object]:
    """The OpenAI completions `logprobs` object for one choice:
    per-token logprob + top-N alternatives + text offsets, computed
    by ONE teacher-forced scoring pass (deterministic model — the
    values equal what decode produced). With `echo`, prompt tokens
    are covered too (position 0 scores as null)."""
    import numpy as np
    lp = rt.score_logprobs(row)                  # [T, vocab]
    start = 0 if echo else prompt_len
    tokens, token_logprobs, top_logprobs, offsets = [], [], [], []
    offset = 0
    for i in range(start, len(row)):
        piece = tok.decode([row[i]])
        tokens.append(piece)
        offsets.append(offset)
        offset += len(piece)
        if i == 0:
            token_logprobs.append(None)
            top_logprobs.append(None)
            continue
        token_logprobs.append(round(float(lp[i - 1, row[i]]), 5))
        if n_top > 0:
            idx = np.argsort(lp[i - 1])[::-1][:n_top]
            top_logprobs.append(
                {tok.decode([int(t)]): round(float(lp[i - 1, t]), 5)
                 for t in idx})
        else:
            top_logprobs.append({})
    return {'tokens': tokens, 'token_logprobs': token_logprobs,
            'top_logprobs': top_logprobs, 'text_offset': offsets}


def run_completion(rt: InferenceRuntime, req: CompletionRequest
                   ) -> Dict[str, object]:
    """Non-streaming completions: returns the OpenAI response dict.
    Each prompt yields `n` choices (indices p*n..p*n+n-1, the OpenAI
    layout for multi-prompt + n)."""
    tok = rt.get_tokenizer()
    t0 = time.monotonic()
    encoded = [tok(p)['input_ids'] for p in req.prompts]
    limit = rt.limit_for(req.temperature)
    for ids in encoded:
        if len(ids) >= limit:
            raise ValueError(f'prompt tokenizes to {len(ids)} >= '
                             f'max_total_len {limit}')
    rows: List[List[int]] = []
    row_prompt: List[List[int]] = []  # prompt ids per output row
    ttft: Optional[float] = None      # engine path latches first commit
    engine = rt.engine_for(req.adapter)
    if req.max_new <= 0:
        # Scoring mode (echo + logprobs + max_tokens=0 — the eval-
        # harness contract): no generation at all.
        for ids in encoded:
            for _ in range(req.n):
                rows.append(list(ids))
                row_prompt.append(ids)
    elif engine is not None:
        from skypilot_tpu.observability.catalog import FirstTokenLatch
        latch = FirstTokenLatch()  # non-streaming TTFT: first commit
        futs = []
        try:
            for ids in encoded:
                for _ in range(req.n):
                    futs.append(engine.submit(
                        ids, max_new_tokens=req.max_new,
                        temperature=req.temperature, top_p=req.top_p,
                        on_token=latch, deadline_s=req.deadline_s,
                        adapter=req.adapter))
                    row_prompt.append(ids)
        except Exception:
            # A shed submission mid-fan-out: cancel the admitted
            # siblings (they would decode for a 429'd client).
            if futs:
                engine.cancel(futs)
            raise
        # Expired requests resolve with DeadlineExceededError from the
        # engine's reaper; the host timeout is only a backstop.
        rows = [f.result(timeout=req.deadline_s + 30.0) for f in futs]
        ttft = latch.first_token_s
    else:
        import jax
        import jax.numpy as jnp
        for ids in encoded:
            # The n samples batch into ONE [n, P] generate call —
            # categorical sampling is independent per row, so this is
            # the n>1 fan-out at full MXU utilization (greedy rows are
            # identical by definition, as in the OpenAI contract).
            want = len(ids) + req.max_new
            bucket = 8
            while bucket < want:
                bucket *= 2
            bucket = min(bucket, limit)
            fn = rt.get_fn(req.n, req.temperature, bucket)
            out = fn(rt.params,
                     jnp.asarray([ids] * req.n, jnp.int32),
                     rt.split_rng())
            got = jax.device_get(out)
            for r in range(req.n):
                rows.append(got[r][:min(want, bucket)].tolist())
                row_prompt.append(ids)

    choices = []
    total_completion = 0
    for i, (ids, row) in enumerate(zip(row_prompt, rows)):
        text = tok.decode(row[len(ids):], skip_special_tokens=True)
        n_gen = len(row) - len(ids)
        finish = 'length' if n_gen >= req.max_new else 'stop'
        text, hit = trim_stops(text, req.stop_strings)
        if hit:
            finish = 'stop'
        total_completion += n_gen
        lp_block = None
        if req.logprobs is not None:
            lp_block = _logprobs_block(rt, tok, row, req.logprobs,
                                       req.echo, len(ids))
        if req.echo:
            text = tok.decode(ids, skip_special_tokens=True) + text
        choices.append({'index': i, 'text': text,
                        'finish_reason': finish,
                        'logprobs': lp_block})
    # Usage counts each PROMPT once (the OpenAI contract): row_prompt
    # holds one entry per choice, so summing it would over-report the
    # prompt n× under n>1.
    total_prompt = sum(len(ids) for ids in encoded)
    rt.metrics.record(time.monotonic() - t0, total_completion,
                      ttft_s=ttft, n_prompt_tokens=total_prompt)
    return {
        'object': 'text_completion',
        'model': req.model or rt.model_name,
        'choices': choices,
        'usage': {
            'prompt_tokens': total_prompt,
            'completion_tokens': total_completion,
            'total_tokens': total_prompt + total_completion,
        },
    }


def stream_completion(rt: InferenceRuntime, req: CompletionRequest,
                      writer, chat: bool = False) -> None:
    """SSE streaming for one prompt x n choices.

    Chunks follow the OpenAI schemas: `text_completion` chunks with
    incremental `text`, or `chat.completion.chunk` deltas ({'role'}
    first, then {'content': ...}) when `chat`. The n choices decode
    CONCURRENTLY (engine slots); their chunks interleave by arrival,
    each tagged with its choice index. Ends with per-choice
    finish_reason chunks and `data: [DONE]`."""
    tok = rt.get_tokenizer()
    ids = tok(req.prompts[0])['input_ids']
    limit = rt.limit_for(req.temperature, streaming=True)
    if len(ids) >= limit:
        raise ValueError(f'prompt tokenizes to {len(ids)} >= '
                         f'max_total_len {limit}')
    t0 = time.monotonic()
    handles = [rt.submit_stream(ids, req.max_new, req.temperature,
                                top_p=req.top_p,
                                deadline_s=req.deadline_s,
                                adapter=req.adapter)
               for _ in range(req.n)]
    writer.sse_start()
    obj = 'chat.completion.chunk' if chat else 'text_completion'
    model_name = req.model or rt.model_name

    def chunk(index: int, text: Optional[str],
              finish: Optional[str] = None) -> Dict[str, object]:
        c: Dict[str, object] = {'index': index,
                                'finish_reason': finish}
        if chat:
            c['delta'] = {} if text is None else {'content': text}
        else:
            c['text'] = text or ''
            c['logprobs'] = None
        return {'object': obj, 'model': model_name,
                'choices': [c]}

    if chat:
        for i in range(req.n):
            writer.sse_send({'object': obj, 'model': model_name,
                             'choices': [{'index': i,
                                          'delta': {'role': 'assistant'},
                                          'finish_reason': None}]})

    decs = [IncrementalDecoder(tok) for _ in range(req.n)]
    scans = [StopStringScanner(req.stop_strings) for _ in range(req.n)]
    n_gen = [0] * req.n
    ttft: Optional[float] = None
    # ITL records at engine commit time (StreamHandle.on_token).

    try:
        for i, t in iter_interleaved(handles):
            if ttft is None:
                ttft = time.monotonic() - t0
            n_gen[i] += 1
            if scans[i].hit:
                continue  # post-stop tokens: drop
            out = scans[i].push(decs[i].push(t))
            if out:
                writer.sse_send(chunk(i, out))
    finally:
        # Disconnected consumer: free the slots NOW instead of
        # decoding tokens nobody reads (no-op on normal completion).
        rt.cancel_streams(handles)
    for i in range(req.n):
        if not scans[i].hit:
            out = scans[i].push(decs[i].flush()) + scans[i].flush()
            if out:
                writer.sse_send(chunk(i, out))
        finish = ('stop' if scans[i].hit
                  else 'length' if n_gen[i] >= req.max_new else 'stop')
        writer.sse_send(chunk(i, None, finish))
    writer.sse_done()
    rt.metrics.record(time.monotonic() - t0, sum(n_gen), ttft_s=ttft,
                      n_prompt_tokens=len(ids))


_warned_no_template = False


def _warn_no_template(reason: str) -> None:
    global _warned_no_template
    if not _warned_no_template:
        _warned_no_template = True
        import sys
        print(f'openai_compat: tokenizer has no usable chat template '
              f'({reason}); falling back to "role: content" prompts.',
              file=sys.stderr, flush=True)


def render_chat_prompt(rt: InferenceRuntime, messages) -> str:
    """Chat template when the checkpoint ships one, else a transparent
    `role: content` fallback (beats a 400 for base models)."""
    tok = rt.get_tokenizer()
    try:
        return tok.apply_chat_template(messages, tokenize=False,
                                       add_generation_prompt=True)
    except Exception as e:  # pylint: disable=broad-except
        # Base models ship no template; say so once instead of letting
        # users puzzle over oddly formatted completions.
        _warn_no_template(f'{type(e).__name__}: {e}')
        return '\n'.join(f"{m['role']}: {m['content']}"
                         for m in messages) + '\nassistant:'


def to_chat_response(out: Dict[str, object]) -> Dict[str, object]:
    out['object'] = 'chat.completion'
    for c in out['choices']:
        c['message'] = {'role': 'assistant', 'content': c.pop('text')}
        lp = c.get('logprobs')
        if lp:
            # Legacy completions block -> modern chat format
            # ({content: [{token, logprob, bytes, top_logprobs}]}).
            content = []
            for token, logprob, top in zip(lp['tokens'],
                                           lp['token_logprobs'],
                                           lp['top_logprobs']):
                content.append({
                    'token': token,
                    'logprob': logprob,
                    'bytes': list(token.encode()),
                    'top_logprobs': [
                        {'token': t, 'logprob': v,
                         'bytes': list(t.encode())}
                        for t, v in sorted((top or {}).items(),
                                           key=lambda kv: -kv[1])],
                })
            c['logprobs'] = {'content': content}
    return out
