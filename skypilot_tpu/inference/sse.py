"""SSE pass-through piping for serving-plane proxies.

Three proxies forward a replica's `text/event-stream` body to a
waiting client: the LB's streaming pass-through, a prefill replica's
handoff proxy, and a migration sender piping its session's tail
through from the new owner. All of them used to loop over requests'
`iter_content(N)` — which BLOCKS until N bytes or EOF. Token frames
are a few dozen bytes, so any stream shorter than N was forwarded in
one burst at EOF: the proxy silently destroyed streaming latency
(TTFT through the LB was the END of the stream) while every timing
metric on the replica itself looked healthy.

`pipe()` forwards bytes as they ARRIVE: urllib3's `read1(n)` returns
whatever the socket currently has (blocking only when there is
nothing), falling back to byte-granular reads on clients without
`read1`. Truncation — the upstream dying or the downstream client
going away — ends the pipe and is reported in the result, never
raised: a proxied stream that breaks mid-flight must look to the
client exactly like a direct replica death, not become a proxy
error after headers are already out.
"""
from __future__ import annotations

import time
from typing import Any, Optional, Tuple

#: read1 budget per syscall — an upper bound, not a wait target.
_CHUNK = 8192


def pipe(upstream: Any, wfile: Any) -> Tuple[bool, Optional[float]]:
    """Pipe `upstream` (a `requests` streamed response) to `wfile`
    with arrival granularity. Returns `(reached_eof, first_at)`;
    `first_at` is the `time.monotonic()` instant the first body
    bytes arrived (None for an empty body), so callers can compute
    TTFT against their own request start."""
    first_at: Optional[float] = None
    raw = getattr(upstream, 'raw', None)
    read1 = getattr(raw, 'read1', None)
    try:
        if read1 is not None:
            while True:
                chunk = read1(_CHUNK)
                if not chunk:
                    return True, first_at
                if first_at is None:
                    first_at = time.monotonic()
                wfile.write(chunk)
                wfile.flush()
        # No read1 on this urllib3: byte-granular reads keep frames
        # flowing at arrival time (CPU-heavier, never buffering).
        for chunk in upstream.iter_content(1):
            if not chunk:
                continue
            if first_at is None:
                first_at = time.monotonic()
            wfile.write(chunk)
            wfile.flush()
        return True, first_at
    except Exception as e:  # pylint: disable=broad-except
        # Upstream death or client disconnect mid-stream: bounded
        # truncation; the caller decides whether and how to log.
        print(f'sse: pipe truncated ({type(e).__name__}: {e})',
              flush=True)
        return False, first_at
