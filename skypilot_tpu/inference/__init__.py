"""In-framework LM inference serving (the payload of serve replicas).

Split of the former monolithic recipes/serve_lm.py:

  - runtime.py       — model/params/engine construction + the request
                       execution surface (one-shot buckets, continuous
                       engine, streaming, TTFT metrics);
  - openai_compat.py — /v1/completions + /v1/chat/completions shims,
                       SSE chunk schemas, incremental detokenization,
                       stop-string scanning, n>1 fan-out;
  - http_server.py   — the HTTP handler (native /generate,
                       /generate_text, /stats) + graceful SIGTERM
                       drain.

`python -m skypilot_tpu.recipes.serve_lm` remains the entry point
(the recipe file is now a thin CLI wrapper over this package).
"""
