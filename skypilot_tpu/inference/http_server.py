"""HTTP front of the inference runtime.

JetStream-shaped native endpoints + OpenAI shims:

  GET  /                       readiness + capacity
  GET  /stats                  engine + serving metrics, JSON
                               (rolling-window percentiles)
  GET  /metrics                Prometheus text exposition of the
                               process registry: engine internals
                               (queue depth, slots, page pool,
                               prefix cache, preemptions) + request
                               path (TTFT/ITL/e2e histograms, token
                               counters) — see docs/guides.md for
                               the metric catalog
  POST /generate               token ids in/out; `stream` = SSE of
                               {"index", "token"} events
  POST /generate_text          text in/out via the --hf tokenizer;
                               `stream` = SSE of {"index", "delta"}
  POST /v1/completions         OpenAI completions (+SSE, n>1)
  POST /v1/chat/completions    OpenAI chat (+SSE, n>1)

Graceful drain on SIGTERM (rolling updates / replica replacement):
stop accepting, wait out in-flight requests up to --drain-grace
seconds, exit 0 via os._exit (skipping the XLA C++ teardown, which is
crash-prone under signal-interleaved shutdown).
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
import uuid as uuid_lib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List

from skypilot_tpu.inference import openai_compat as oai
from skypilot_tpu.inference import sse
from skypilot_tpu.inference.runtime import (InferenceRuntime,
                                            iter_interleaved)
from skypilot_tpu.observability import REGISTRY
from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.observability import tracing
from skypilot_tpu.ops import pallas_paged as _pallas_paged
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness import train_guard
from skypilot_tpu.robustness.errors import (AdapterLoadError,
                                            AdapterNotFoundError,
                                            DeadlineExceededError,
                                            EngineDeadError,
                                            QueueSaturatedError,
                                            SessionMigratedError)


#: This process's replica instance identity, echoed in `GET /stats`.
#: The replica plane's manager journals the UUID it handed the
#: process at spawn (STPU_REPLICA_INSTANCE_UUID) and, on controller
#: restart, adopts a pid/port only if the echo matches — a recycled
#: pid or a stranger's server on the old port fails the check.
#: Standalone servers mint their own (adoption simply never matches
#: a replica the journal does not know).
INSTANCE_UUID = (os.environ.get('STPU_REPLICA_INSTANCE_UUID') or
                 uuid_lib.uuid4().hex)


def classify_error(e: Exception):
    """(http_status, retry_after_s) for a request-path exception: the
    robustness taxonomy (429 shed / 504 deadline / 503 engine dead or
    adapter load failure / 404 unknown model) ahead of the 400
    catch-all."""
    if isinstance(e, QueueSaturatedError):
        return 429, e.retry_after_s
    if isinstance(e, DeadlineExceededError):
        return 504, None
    if isinstance(e, SessionMigratedError):
        # Resume failed end to end (peer ship AND local replay): 503
        # is retryable — the LB resubmits on another replica instead
        # of surfacing the evacuation to the client.
        return 503, 0.5
    if isinstance(e, (EngineDeadError, AdapterLoadError)):
        return 503, None
    if isinstance(e, AdapterNotFoundError):
        return 404, None
    return 400, None


def _submit_all(engine, rows: List[List[int]], **kw):
    """Submit one request's rows; if submission k is shed (bounded
    queue filled mid-batch), cancel the k-1 already-submitted rows —
    they would decode for a client that is getting a 429."""
    futs = []
    try:
        for row in rows:
            futs.append(engine.submit(row, **kw))
    except Exception:
        if futs:
            engine.cancel(futs)
        raise
    return futs


def make_server(rt: InferenceRuntime,
                port: int) -> ThreadingHTTPServer:
    """Build the (not yet serving) HTTP server for `rt`. Split from
    `serve()` so tests can run it on an ephemeral port from a thread
    (serve() additionally installs the SIGTERM drain, which only
    works on the main thread). The in-flight POST count rides on the
    returned server as `.inflight`/`.inflight_lock`."""

    # Live POSTs (graceful drain waits on this, covering the window
    # between accept and engine submit and the one-shot engine).
    _inflight = {'n': 0}
    _inflight_lock = threading.Lock()
    # Rolling-update drain: set before the accept loop stops, so
    # /readyz flips to 503 while in-flight requests finish (k8s
    # readiness probes pull the replica out of rotation first).
    _draining = threading.Event()

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, *a):  # quiet
            pass

        # -- writer surface (also used by openai_compat) ------------
        def _json(self, obj, code=200, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def send_json(self, obj, code=200):
            self._json(obj, code)

        def sse_start(self):
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-cache')
            self.send_header('Connection', 'close')
            self.end_headers()
            self._sse_open = True

        def sse_send(self, obj):
            self.wfile.write(b'data: ' + json.dumps(obj).encode() +
                             b'\n\n')
            self.wfile.flush()

        def sse_done(self):
            self.wfile.write(b'data: [DONE]\n\n')
            self.wfile.flush()

        # -- GET ----------------------------------------------------
        def do_GET(self):  # noqa: N802
            if self.path == '/healthz':
                # Liveness: the process is up and serving HTTP. Never
                # reflects load or drains — k8s restarts on liveness
                # failure, and restarting a merely-busy replica is
                # how overload cascades start.
                self._json({'status': 'alive'})
                return
            if self.path == '/readyz':
                self._readyz()
                return
            if self.path in ('/stats', '/v1/stats'):
                self._stats()
                return
            if self.path in ('/metrics', '/v1/metrics'):
                self._prometheus_metrics()
                return
            if self.path.startswith('/debug/trace/'):
                self._debug_trace(
                    self.path[len('/debug/trace/'):].strip('/'))
                return
            if self.path == '/debug/flight':
                self._debug_flight()
                return
            if self.path == '/v1/models':
                # OpenAI client bootstrap: most SDKs list models
                # before first use. Adapters are models: the `model`
                # field on /v1/* selects one (base name = base model).
                names = [rt.model_name]
                if rt.adapters is not None:
                    names += rt.adapters.inventory()
                self._json({'object': 'list',
                            'data': [{'id': name,
                                      'object': 'model',
                                      'owned_by': 'skypilot-tpu'}
                                     for name in names]})
                return
            # Advertise the MINIMUM capacity across request classes
            # (speculative clamp, decode-chunk clamp) — clients sizing
            # prompts off this can never be rejected.
            self._json({'status': 'ok',
                        'model': rt.model_name,
                        'vocab_size': rt.vocab_size,
                        'max_total_len': min(rt.limit_for(0.0),
                                             rt.limit_for(1.0))})

        def _readyz(self):
            """Readiness: should this replica receive NEW traffic?
            503 while draining (SIGTERM received), when an engine's
            scheduler thread died, or when the bounded queue is
            saturated — each with the reason, so `kubectl describe`
            (or a curl) says WHY the replica left rotation."""
            reasons = []
            if _draining.is_set():
                reasons.append('draining')
            for eng in rt.live_engines():
                if not eng.healthy():
                    reasons.append('engine dead')
                if eng.saturated():
                    reasons.append('queue saturated')
            self._json({'ready': not reasons, 'reasons': reasons},
                       200 if not reasons else 503)

        def _debug_trace(self, trace_id):
            """Completed spans THIS process recorded for one trace,
            as a Chrome-trace JSON body. `stpu trace` fetches this
            from every fleet process and merges on the shared
            trace_id."""
            body = tracing.get_trace(trace_id)
            if body is None:
                self._json({'error': f'unknown trace {trace_id!r}',
                            'known': tracing.trace_ids()[-16:]}, 404)
                return
            self._json(body)

        def _debug_flight(self):
            """Flight-recorder dump of every live engine: the last N
            scheduler events (admit, chunk dispatch, round commit,
            preemption, eviction, spill, restore, handoff, soft
            error, reset), recorded unconditionally — the post-mortem
            readout when a replica wedges or dies."""
            self._json({
                'instance_uuid': INSTANCE_UUID,
                'pid': os.getpid(),
                'role': rt.role,
                'engines': [eng.flight.dump()
                            for eng in rt.live_engines()],
            })

        def _prometheus_metrics(self):
            """Prometheus text exposition of the process registry.
            Snapshot gauges (queue depth, slot occupancy, page pool)
            refresh from live engine state at scrape time; counters
            and histograms tick at their event sites."""
            for eng in rt.live_engines():
                eng.update_metric_gauges()
            body = REGISTRY.render().encode()
            self.send_response(200)
            self.send_header('Content-Type', REGISTRY.CONTENT_TYPE)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stats(self):
            """Engine observability (the vLLM /metrics idea, JSON):
            slot occupancy, page pool, prefix-cache hit rate,
            speculation quality, and serving latency percentiles
            over the rolling window documented by the `window` key
            (GET /metrics carries the same signals as lifetime
            Prometheus series)."""
            engine = rt.engine
            body = {'serving': rt.metrics.snapshot(),
                    'instance_uuid': INSTANCE_UUID,
                    'pid': os.getpid(),
                    # Disaggregated serving: '' = unified replica.
                    'role': rt.role,
                    # Quantized-serving storage formats + weight
                    # footprint (docs/guides.md "Quantized serving").
                    'storage': {
                        'kv_dtype': rt.kv_dtype,
                        'weight_dtype': rt.weight_dtype,
                        'weight_bytes': rt.weight_bytes,
                        # Mesh-sharded serving (docs/guides.md
                        # "Sharded serving"): devices the engines'
                        # state spans (1 = single device).
                        'mesh_devices': rt.mesh_devices,
                        # Pipeline-parallel serving (--stages): stage
                        # count of the (stage, tensor) mesh (1 = no
                        # stage split; tensor ways = mesh_devices /
                        # stages).
                        'stages': rt.stages,
                        # Fused kernel path (docs/guides.md "Fused
                        # kernel path & roofline"): why the COMPILED
                        # pallas route is unavailable here, or null
                        # when it can run (interpret mode always can).
                        'attention_kernel_unavailable_reason':
                            _pallas_paged.unavailable_reason(),
                    }}
            if rt.role or rt.handoffs_total or rt.kv_imports_total:
                body['handoff'] = rt.handoff_stats()
            mig = rt.migration_stats()
            if mig['sessions_evacuated'] or mig['migrations'] or \
                    mig['migrations_in']:
                # Live migration: out/in counts, recompute cost, and
                # the migrated-in affinity keys the fleet controller
                # pins at the LB so follow-ups land on the warm pages.
                body['migration'] = mig
            if rt.adapters is not None:
                body['adapters'] = rt.adapters.stats()
            if rt.slo_tracker is not None:
                body['slo'] = rt.slo_tracker.snapshot()
            if engine is None:
                body['engine'] = 'simple'
                self._json(body)
                return
            engine.update_metric_gauges()
            body.update({
                'engine': 'continuous',
                'num_slots': engine.num_slots,
                'active_slots': int(engine.active.sum()),
                'queued': engine._queue.qsize() + len(engine._ready),
                'decode_calls': engine.decode_calls,
                'tokens_committed': engine.tokens_committed,
                'tokens_per_call': round(
                    engine.tokens_committed /
                    max(engine.decode_calls, 1), 3),
                'speculative_k': engine.spec_k,
                'preemptions': engine.preemptions,
                # Stall-free scheduler: chunked-prefill + pipelining
                # health (docs/guides.md serving-tuning section).
                'prefill_chunk': engine.prefill_chunk,
                'prefill_token_budget': engine.prefill_budget,
                'pipeline_decode': engine.pipeline_decode,
                'prefill_chunks_run': engine.prefill_chunks_run,
                'prefill_backlog_tokens':
                    engine.prefill_backlog_tokens(),
                'decode_stall_s': round(engine.decode_stall_s, 4),
                # Pipeline-parallel serving (--stages): stage count
                # and the closed-form (S-1)/(M+S-1) fill/drain bubble
                # of the last prefill burst (0.0 when unstaged).
                'pipeline_stages': engine.stages,
                'prefill_bubble_fraction': round(
                    engine._prefill_bubble, 6),
                # Fused kernel path + analytic HBM roofline inputs
                # (ops/pallas_paged.py; serve_bench scores achieved
                # tokens/s against bytes_per_token * HBM peak).
                'attention_impl': engine.attention_impl(),
                'attention_bytes_per_token':
                    engine.attention_bytes_per_token(),
                # Robustness plane (docs/guides.md serving-robustness
                # section): shedding, deadlines, crash containment.
                'healthy': engine.healthy(),
                'requests_shed': engine.requests_shed,
                'deadline_exceeded': engine.deadline_exceeded,
                'engine_restarts': engine.engine_restarts,
                'queued_tokens': engine.queued_tokens(),
                'max_queue_requests': engine.max_queue_requests,
                'max_queue_tokens': engine.max_queue_tokens,
            })
            if engine.paged:
                free = int(engine.allocator.free_pages)
                body['page_pool'] = {
                    'total': engine.total_pages,
                    'free': free,
                    'used': engine.total_pages - free,
                    'utilization': round(
                        (engine.total_pages - free) /
                        max(engine.total_pages, 1), 3),
                    'kv_dtype': engine.kv_dtype,
                    'pool_bytes': engine.kv_cache_bytes(),
                    # Per-chip view of the sharded pool: bytes ONE
                    # device holds and how many ways the kv-heads
                    # axis actually split (1 = replicated — single
                    # device or the GQA remainder rule fired).
                    'pool_bytes_per_device':
                        engine.kv_cache_bytes_per_device(),
                    'shard_ways': engine.kv_shard_ways,
                }
                if engine.stages > 1:
                    # Staged pool split: every stage stores the same
                    # page indices (one shared allocator) but only
                    # its own layer range's bytes.
                    body['page_pool']['stages'] = \
                        engine.stage_pool_stats()
                if engine.prefix_cache is not None:
                    pc = engine.prefix_cache
                    body['prefix_cache'] = {
                        'hits': pc.hits,
                        'misses': pc.misses,
                        'hit_rate': round(
                            pc.hits / max(pc.hits + pc.misses, 1), 3),
                        'evictions': pc.evictions,
                        'resident_unreferenced': len(pc.lru),
                    }
                if engine.spill_tier is not None:
                    # Tiered cache: the host/cold spill tier's own
                    # accounting + the engine-level restore outcome
                    # (docs/guides.md "Disaggregated serving & cache
                    # tiering").
                    spill = engine.spill_tier.stats()
                    spill.update({
                        'restore_lookups': engine.kv_restore_lookups,
                        'restore_hits': engine.kv_restore_hits,
                        'restored_into_pool':
                            engine.kv_restored_pages,
                    })
                    body['kv_spill'] = spill
            self._json(body)

        # -- POST ---------------------------------------------------
        def do_POST(self):  # noqa: N802
            with _inflight_lock:
                _inflight['n'] += 1
            try:
                self._do_post()
            finally:
                with _inflight_lock:
                    _inflight['n'] -= 1

        def _read_body(self):
            # The KV-handoff paths re-dispatch an embedded request
            # into the normal handlers; the injected body stands in
            # for the (already consumed) socket payload.
            injected = getattr(self, '_injected_body', None)
            if injected is not None:
                self._injected_body = None
                return injected
            length = int(self.headers.get('Content-Length', 0))
            return json.loads(self.rfile.read(length))

        def _route_generation(self, path):
            """Generation-path handler for `path`, or None. Shared by
            the normal POST dispatch and the /kv/import embedded-
            request re-dispatch (the decode side of a handoff)."""
            if path == '/v1/completions':
                return self._openai_completions
            if path == '/v1/chat/completions':
                return self._openai_chat
            if path in ('/generate_text', '/v1/generate_text'):
                return self._generate_text
            if path in ('/generate', '/v1/generate'):
                return self._generate
            return None

        def _do_post(self):
            if faults.point('http.handler') is faults.DROP:
                return  # injected blackhole: client sees a hang/reset
            # Adopt the caller's trace (LB or prefill peer sent the
            # x-skypilot-trace header) or make the head-sampling
            # decision here; unsampled = one float compare, no span.
            ctx = tracing.parse_header(
                self.headers.get(tracing.HEADER))
            if ctx is None:
                ctx = tracing.new_ctx()
            if ctx is None:
                self._trace_ctx = None
                self._dispatch_post()
                return
            with tracing.span('replica.request', ctx,
                              process=rt.role or 'replica',
                              path=self.path) as root:
                # Children (engine spans, handoff spans) parent to
                # this request root, not to the wire parent.
                self._trace_ctx = root.ctx
                self._dispatch_post()

        def _dispatch_post(self):
            if self.path == '/kv/import':
                self._kv_import()
                return
            if self.path == '/kv/peers':
                self._kv_peers()
                return
            if self.path == '/kv/evacuate':
                self._kv_evacuate()
                return
            if self.path == '/kv/migrate':
                self._kv_migrate()
                return
            handler = self._route_generation(self.path)
            if handler is None:
                self._json({'error': 'POST /generate, /generate_text, '
                                     'or /v1/completions'}, 404)
                return
            if rt.role == 'prefill':
                try:
                    body = self._read_body()
                except (ValueError, OSError):
                    body = None  # malformed: the handler's 400 to give
                if body is not None:
                    if self._maybe_handoff(self.path, body):
                        return
                    self._injected_body = body
            handler()

        # -- disaggregated prefill/decode handoff -------------------
        def _kv_peers(self):
            """Fleet-controller push of the decode pool this prefill
            replica hands off to."""
            try:
                req = self._read_body()
                peers = [str(p) for p in (req.get('decode') or [])]
                rt.set_decode_peers(peers)
                self._json({'decode': peers})
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': f'{type(e).__name__}: {e}'}, 400)

        def _kv_import(self):
            """Decode side of a handoff: scatter the POSTed page
            chain into the pool + prefix cache and — when the body
            embeds the original request — serve it immediately: the
            admission finds every full prompt page already resident,
            so the request enters decoding with only the sub-page
            prompt tail recomputed (no re-prefill)."""
            import base64
            try:
                req = self._read_body()
                data = base64.b64decode(req['payload'])
                eng = rt.engine if rt.engine is not None \
                    else rt.stream_engine()
                with tracing.span('kv.import',
                                  getattr(self, '_trace_ctx', None),
                                  bytes=len(data)):
                    summary = eng.import_chain(data)
                rt.record_kv_import(summary)
            except Exception as e:  # pylint: disable=broad-except
                self._plain_error(e)
                return
            inner = req.get('request')
            if not inner:
                self._json({'imported': summary})
                return
            inner_path = str(req.get('path') or '/generate')
            handler = self._route_generation(inner_path)
            if handler is None:
                self._json({'error': f'unroutable handoff path '
                                     f'{inner_path!r}'}, 400)
                return
            self.path = inner_path
            self._injected_body = inner
            handler()

        # -- live KV-chain migration --------------------------------
        def _kv_evacuate(self):
            """Controller-initiated evacuation: a scale-down drain
            POSTs {reason: 'drain'} before SIGTERM, a rebalance POSTs
            {reason: 'rebalance', target, max_sessions}. Every
            evacuated session's future resolves with
            SessionMigratedError; the owning HTTP threads ship the
            chains (to `target` when given, else the peer ring picks)
            and proxy the tails. Responds with the evacuation count —
            the migrations themselves complete asynchronously on
            those threads."""
            try:
                req = self._read_body()
            except (ValueError, OSError):
                req = {}
            reason = str(req.get('reason') or 'drain')
            target = req.get('target') or None
            max_sessions = req.get('max_sessions')
            if max_sessions is not None:
                max_sessions = int(max_sessions)
            rt.set_evacuation_hint(reason, target)
            total = {'evacuated': 0, 'chains': 0, 'queued': 0}
            try:
                for eng in rt.live_engines():
                    fn = getattr(eng, 'evacuate_chains', None)
                    if fn is None:
                        continue
                    s = fn(max_sessions=max_sessions, reason=reason)
                    for k in total:
                        total[k] += int(s.get(k, 0))
                rt.record_evacuation(total)
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': f'{type(e).__name__}: {e}'}, 500)
                return
            self._json(dict(total, reason=reason))

        def _kv_migrate(self):
            """Receiving side of a live migration: import the packed
            committed-token chain (when one shipped), account the
            re-prefill cost and the session's affinity key (the ring
            /stats exposes for LB pinning), then serve the embedded
            continuation request — admission finds the committed full
            pages resident, so only the sub-page tail recomputes and
            greedy decoding continues bit-identically."""
            import base64
            try:
                req = self._read_body()
                inner = req.get('request') or {}
                rows = inner.get('tokens') or []
                row = ([int(t) for t in rows[0]]
                       if rows and isinstance(rows[0], list) else [])
                eng = rt.engine if rt.engine is not None \
                    else rt.stream_engine()
                summary = {'pages': 0, 'imported': 0,
                           'already_cached': 0, 'dropped': 0}
                if req.get('payload'):
                    data = base64.b64decode(req['payload'])
                    with tracing.span('kv.import',
                                      getattr(self, '_trace_ctx',
                                              None),
                                      bytes=len(data)):
                        summary = eng.import_chain(data)
                    rt.record_kv_import(summary)
                page_size = int(getattr(eng, 'page_size', 0) or 0)
                covered = (summary['imported'] +
                           summary['already_cached']) * page_size
                recomputed = max(0, len(row) - covered) if row else 0
                key = None
                if row and getattr(eng, 'paged', False):
                    from skypilot_tpu.inference import affinity
                    key = affinity.token_affinity_key(
                        row, page_size,
                        salt=affinity.adapter_salt(inner.get('model')))
                rt.record_migrated_in(key, recomputed)
            except Exception as e:  # pylint: disable=broad-except
                self._plain_error(e)
                return
            if not inner:
                self._json({'imported': summary})
                return
            inner_path = str(req.get('path') or '/generate')
            handler = self._route_generation(inner_path)
            if handler is None:
                self._json({'error': f'unroutable migration path '
                                     f'{inner_path!r}'}, 400)
                return
            self.path = inner_path
            self._injected_body = inner
            handler()

        def _migrate_record(self, rec, stream):
            """Ship one evacuated session to a peer: POST the chain +
            continuation request to /kv/migrate and return the open
            upstream response (the caller proxies body or SSE tail).
            None on ANY failure — injected kv.migrate fault, no peer,
            peer refused — and the caller resumes locally on the
            promoted warm pages."""
            import base64

            import requests as requests_lib
            reason = str(rec.get('reason') or 'drain')
            _hint_reason, target = rt.evacuation_hint()
            t0 = time.monotonic()
            try:
                if faults.point('kv.migrate',
                                reason=reason) is faults.DROP:
                    raise RuntimeError('injected kv.migrate drop')
                tokens = [int(t) for t in rec.get('tokens') or []]
                if not tokens:
                    raise RuntimeError('empty migration record')
                remaining = int(rec.get('limit', 0)) - len(tokens)
                if remaining <= 0:
                    raise RuntimeError('no generation budget left')
                peer = target
                if peer is None:
                    from skypilot_tpu.inference import affinity
                    eng = next(iter(rt.live_engines()), None)
                    key = None
                    if eng is not None and getattr(eng, 'paged',
                                                   False):
                        key = affinity.token_affinity_key(
                            tokens, eng.page_size,
                            salt=affinity.adapter_salt(
                                rec.get('adapter')))
                    peer = rt.pick_decode_peer(key)
                if not peer:
                    raise RuntimeError('no migration peer available')
                inner = {'tokens': [tokens],
                         'max_new_tokens': remaining,
                         'temperature': rec.get('temperature', 0.0),
                         'top_k': rec.get('top_k', 0),
                         'top_p': rec.get('top_p', 1.0),
                         'stop_token_ids':
                             rec.get('stop_token_ids') or [],
                         'stream': bool(stream)}
                if rec.get('adapter'):
                    inner['model'] = rec['adapter']
                if rec.get('deadline_s'):
                    inner['timeout'] = rec['deadline_s']
                body = {'path': '/generate', 'request': inner,
                        'reason': reason}
                if rec.get('payload'):
                    body['payload'] = base64.b64encode(
                        rec['payload']).decode()
                ctx = getattr(self, '_trace_ctx', None)
                hdrs = ({tracing.HEADER: tracing.format_header(ctx)}
                        if ctx is not None else None)
                read_timeout = float(rec.get('deadline_s') or
                                     rt.request_timeout) + 60.0
                with tracing.span('kv.migrate', ctx, peer=peer,
                                  reason=reason):
                    upstream = requests_lib.post(
                        f'http://{peer}/kv/migrate', json=body,
                        headers=hdrs, stream=True,
                        timeout=(3.0, read_timeout))
                if upstream.status_code != 200:
                    code = upstream.status_code
                    upstream.close()
                    raise RuntimeError(
                        f'migration peer {peer} answered {code}')
            except Exception as e:  # pylint: disable=broad-except
                rt.record_migration(reason, time.monotonic() - t0,
                                    ok=False)
                print(f'kv migrate failed ({type(e).__name__}: {e}); '
                      f'resuming locally', flush=True)
                return None
            rt.record_migration(reason, time.monotonic() - t0,
                                ok=True)
            return upstream

        def _resume_record(self, rec, depth: int = 0):
            """Finish one evacuated (non-streaming) session: try the
            peer ship, fall back to a local warm resume. Returns the
            full token row (prompt + all generated)."""
            upstream = self._migrate_record(rec, stream=False)
            if upstream is not None:
                try:
                    with upstream:
                        out = upstream.json()
                    rows = out.get('tokens') or []
                    if rows and isinstance(rows[0], list):
                        return [int(t) for t in rows[0]]
                except Exception as e:  # pylint: disable=broad-except
                    print(f'kv migrate response unusable '
                          f'({type(e).__name__}: {e}); resuming '
                          f'locally', flush=True)
            return self._resume_locally(rec, depth=depth)

        def _resume_locally(self, rec, depth: int = 0):
            """Local warm resume of an evacuated session: resubmit
            the committed tokens — their full pages were promoted
            into the prefix cache at evacuation, so admission is a
            prefix-cache hit and only the sub-page tail recomputes.
            A second evacuation mid-resume retries the whole ladder
            (bounded); success counts as a 'local_fallback'
            migration."""
            tokens = [int(t) for t in rec.get('tokens') or []]
            remaining = max(int(rec.get('limit', 0)) - len(tokens), 1)
            adapter = rec.get('adapter')
            eng = rt.engine_for(adapter)
            if eng is None:
                return tokens  # one-shot runtime: nothing to resume
            deadline_s = (float(rec.get('deadline_s') or 0)
                          or rt.request_timeout)
            t0 = time.monotonic()
            try:
                fut = eng.submit(
                    tokens, max_new_tokens=remaining,
                    temperature=rec.get('temperature', 0.0),
                    top_k=rec.get('top_k', 0),
                    top_p=rec.get('top_p', 1.0),
                    stop_token_ids=list(
                        rec.get('stop_token_ids') or []),
                    deadline_s=deadline_s, adapter=adapter,
                    trace_ctx=getattr(self, '_trace_ctx', None))
                row = fut.result(timeout=deadline_s + 30.0)
            except SessionMigratedError as me:
                if depth >= 2:
                    raise
                return self._resume_record(me.record, depth=depth + 1)
            rt.record_migration('local_fallback',
                                time.monotonic() - t0, ok=True)
            return row

        def _maybe_handoff(self, path, req) -> bool:
            """Prefill-role disaggregation: prefill the prompt
            locally (1-token generation — its pages promote into the
            prefix cache), export the page chain, POST it with the
            original request to the affinity-assigned decode peer,
            and proxy that peer's response back. True = the client
            was fully answered from the decode pool. ANY failure —
            injected kv.handoff fault, unreachable peer, decode-side
            shed (429/503) — returns False and the caller serves the
            request locally from the already-warm pages (graceful
            fallback, never a client-visible error)."""
            peers = rt.decode_peers()
            eng = rt.engine
            if not peers or eng is None or \
                    not getattr(eng, 'prefix_caching', False):
                return False
            if path not in ('/generate', '/v1/generate'):
                # Text endpoints have no token ids here; they serve
                # locally on the prefill replica (the LB's length
                # threshold only routes token requests this way).
                return False
            rows = req.get('tokens') or []
            if not rows or not isinstance(rows[0], list) or \
                    len(rows) != 1:
                return False  # batch rows: local (no chain per row)
            import base64

            import requests as requests_lib

            from skypilot_tpu.inference import affinity
            ctx = getattr(self, '_trace_ctx', None)
            t0 = time.monotonic()
            nbytes = 0
            try:
                if faults.point('kv.handoff') is faults.DROP:
                    raise RuntimeError('injected kv.handoff drop')
                row = [int(t) for t in rows[0]]
                adapter = rt.resolve_model(req.get('model'))
                deadline_s = rt.deadline_for(req)
                limit = rt.limit_for(0.0, streaming=True)
                if len(row) >= limit:
                    return False  # the handler's 400 to give
                # Local prefill: ONE generated token forces the
                # prompt through the (chunked) prefill path and
                # promotes its full pages into the prefix cache.
                eng.submit(row, max_new_tokens=1, temperature=0.0,
                           deadline_s=deadline_s,
                           adapter=adapter,
                           trace_ctx=ctx).result(
                               timeout=deadline_s + 30.0)
                with tracing.span('kv.export', ctx) as sp:
                    data = eng.export_chain(row, adapter=adapter)
                    sp.add(bytes=len(data))
                if not data:
                    return False  # sub-page prompt: nothing to ship
                key = affinity.token_affinity_key(
                    row, eng.page_size,
                    salt=affinity.adapter_salt(req.get('model')))
                peer = rt.pick_decode_peer(key)
                if peer is None:
                    return False
                nbytes = len(data)
                # The trace rides the handoff: the decode peer's
                # root span adopts this trace_id, completing the
                # LB -> prefill -> decode chain.
                hdrs = ({tracing.HEADER: tracing.format_header(ctx)}
                        if ctx is not None else None)
                with tracing.span('kv.post', ctx, peer=peer,
                                  bytes=nbytes):
                    upstream = requests_lib.post(
                        f'http://{peer}/kv/import',
                        json={'payload':
                              base64.b64encode(data).decode(),
                              'path': path, 'request': req},
                        headers=hdrs,
                        stream=True,
                        timeout=(3.0, deadline_s + 60.0))
                if upstream.status_code in (429, 500, 502, 503):
                    code = upstream.status_code
                    upstream.close()
                    raise RuntimeError(
                        f'decode replica {peer} answered {code}')
            except Exception as e:  # pylint: disable=broad-except
                rt.record_handoff(time.monotonic() - t0, nbytes,
                                  ok=False)
                print(f'kv handoff failed ({type(e).__name__}: {e}); '
                      f'serving locally', flush=True)
                return False
            # Stream the decode replica's response through. Headers
            # out = the handoff is committed; a mid-stream death
            # truncates exactly like a direct replica death would.
            rt.record_handoff(time.monotonic() - t0, nbytes, ok=True)
            with upstream:
                self.send_response(upstream.status_code)
                ctype = upstream.headers.get('Content-Type',
                                             'application/json')
                self.send_header('Content-Type', ctype)
                body_bytes = None
                if 'text/event-stream' not in ctype:
                    body_bytes = upstream.content
                    self.send_header('Content-Length',
                                     str(len(body_bytes)))
                self.end_headers()
                if body_bytes is not None:
                    self.wfile.write(body_bytes)
                    return True
                self._sse_open = True
                eof, _first = sse.pipe(upstream, self.wfile)
                if not eof:
                    print('kv handoff stream truncated', flush=True)
            return True

        def _generate(self):
            try:
                req = self._read_body()
                tokens = req['tokens']
                temperature = float(req.get('temperature', 0.0))
                top_k = int(req.get('top_k', 0))
                top_p = float(req.get('top_p', 1.0))
                stop_ids = [int(t) for t in
                            req.get('stop_token_ids', [])]
                stream = bool(req.get('stream'))
                # `model` selects a LoRA adapter (unknown -> 404; base
                # name / absent -> base model).
                adapter = rt.resolve_model(req.get('model'))
                deadline_s = rt.deadline_for(req)
                limit = rt.limit_for(temperature, streaming=stream)
                for row in tokens:
                    if len(row) >= limit:
                        raise ValueError(
                            f'prompt len {len(row)} >= max_total_len '
                            f'{limit}')
                max_new = int(req.get('max_new_tokens',
                                      rt.engine_total))
                if stream:
                    self._generate_stream(tokens, max_new, temperature,
                                          top_k, top_p, stop_ids,
                                          deadline_s, adapter)
                    return
                t0 = time.monotonic()
                ttft = None
                eng = rt.engine_for(adapter)
                if eng is not None:
                    # Ragged rows welcome: each joins the shared
                    # decode loop independently. The shared latch
                    # records TTFT at the request's FIRST committed
                    # token (any row) — non-streaming requests get
                    # real TTFT too, not just streamed ones.
                    latch = obs_catalog.FirstTokenLatch()
                    futs = _submit_all(
                        eng,
                        [[int(t) for t in row] for row in tokens],
                        max_new_tokens=max_new,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, stop_token_ids=stop_ids,
                        on_token=latch, deadline_s=deadline_s,
                        adapter=adapter,
                        trace_ctx=getattr(self, '_trace_ctx', None))
                    # The engine's deadline sweep resolves expired
                    # futures with DeadlineExceededError (-> 504); the
                    # host-side timeout is only a backstop. A future
                    # resolving with SessionMigratedError means the
                    # engine evacuated the slot (drain / preemption /
                    # rebalance): finish that row on a peer, or
                    # locally on the promoted warm pages.
                    rows = []
                    for f in futs:
                        try:
                            rows.append(f.result(
                                timeout=deadline_s + 30.0))
                        except SessionMigratedError as me:
                            rows.append(self._resume_record(
                                me.record))
                    ttft = latch.first_token_s
                else:
                    import jax
                    import jax.numpy as jnp
                    prompt = jnp.asarray(tokens, jnp.int32)
                    if prompt.ndim != 2:
                        raise ValueError(
                            'tokens must be [batch, prompt_len]')
                    fn = rt.get_fn(prompt.shape[0], temperature)
                    out = fn(rt.params, prompt, rt.split_rng())
                    rows = jax.device_get(out).tolist()
                # One-shot rows come back padded to the full jit
                # bucket: the DECODED count is bounded by max_new,
                # not the buffer tail (metrics feed /stats tok/s).
                n_gen = sum(min(max(len(r) - len(p), 0), max_new)
                            for r, p in zip(rows, tokens))
                rt.metrics.record(time.monotonic() - t0, n_gen,
                                  ttft_s=ttft,
                                  n_prompt_tokens=sum(
                                      len(p) for p in tokens))
                self._json({'tokens': rows})
            except Exception as e:  # pylint: disable=broad-except
                self._plain_error(e)

        def _robustness_accounting(self, e: Exception):
            """(code, headers) for a failed request + the shed /
            deadline counters (window stats + Prometheus)."""
            code, retry_after = classify_error(e)
            if code == 429:
                rt.metrics.record_shed()
            elif code == 504:
                rt.metrics.record_deadline_exceeded()
            elif code == 503 and rt.metrics.slo is not None:
                # Engine-dead / adapter-load failures are server
                # errors: they burn error budget (429/504 already
                # burn through their own hooks; 4xx client errors
                # never do).
                rt.metrics.slo.record_request(error=True)
            headers = ({'Retry-After': str(max(1, int(retry_after)))}
                       if retry_after is not None else None)
            return code, headers

        def _plain_error(self, e: Exception):
            code, headers = self._robustness_accounting(e)
            if getattr(self, '_sse_open', False):
                # Mid-stream failure: headers are out; close the
                # stream (the client sees truncation, not a reset).
                try:
                    self.sse_done()
                except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — closing an already-broken stream; client is gone
                    pass
                return
            self._json({'error': f'{type(e).__name__}: {e}'}, code,
                       headers=headers)

        def _generate_stream(self, tokens, max_new, temperature,
                             top_k, top_p, stop_ids, deadline_s,
                             adapter=None):
            """SSE of {"index": row, "token": id} events, one per
            committed token across all rows, interleaved by arrival."""
            t0 = time.monotonic()
            handles = [rt.submit_stream(
                [int(t) for t in row], max_new, temperature,
                top_k=top_k, top_p=top_p, stop_token_ids=stop_ids,
                deadline_s=deadline_s, adapter=adapter,
                trace_ctx=getattr(self, '_trace_ctx', None))
                for row in tokens]
            self.sse_start()
            n_gen = 0
            ttft = None
            migrated = False
            # ITL is recorded at engine commit time by the handles'
            # on_token (StreamHandle), not at SSE delivery.
            try:
                try:
                    for i, t in iter_interleaved(handles):
                        if ttft is None:
                            ttft = time.monotonic() - t0
                        n_gen += 1
                        self.sse_send({'index': i, 'token': t})
                except SessionMigratedError:
                    # The engine evacuated the slots mid-stream. The
                    # interleaver drained every already-committed
                    # token first, so the client is exactly caught up
                    # with the committed sequence — finish the tail
                    # from a peer (or locally) below.
                    migrated = True
            finally:
                rt.cancel_streams(handles)  # no-op when completed
            if migrated:
                final_rows = self._finish_migrated_stream(handles)
                if final_rows is None:
                    # Fully proxied: the peer's SSE tail (terminal
                    # event + [DONE] included) already went out.
                    rt.metrics.record(time.monotonic() - t0, n_gen,
                                      ttft_s=ttft,
                                      n_prompt_tokens=sum(
                                          len(row) for row in tokens))
                    return
            else:
                final_rows = [h.future.result() for h in handles]
            # Full rows in the terminal event: stream consumers get
            # the same payload the non-streaming endpoint returns.
            self.sse_send({'done': True, 'tokens': final_rows})
            self.sse_done()
            rt.metrics.record(time.monotonic() - t0, n_gen,
                              ttft_s=ttft,
                              n_prompt_tokens=sum(
                                  len(row) for row in tokens))

        def _finish_migrated_stream(self, handles):
            """Finish an SSE /generate stream whose slots were
            evacuated mid-flight. Single-row streams proxy the peer's
            SSE tail straight through (same {'index': 0, ...} frame
            shape, terminal event included) — returns None. Multi-row
            streams, and any ship failure, resume locally: the
            continuation tokens keep streaming under their original
            row indices and the full rows come back for the terminal
            event."""
            outcomes = []
            for h in handles:
                try:
                    outcomes.append(('done',
                                     h.future.result(timeout=0.001)))
                except SessionMigratedError as me:
                    outcomes.append(('rec', me.record))
            recs = [(i, o[1]) for i, o in enumerate(outcomes)
                    if o[0] == 'rec']
            if len(handles) == 1 and recs:
                upstream = self._migrate_record(recs[0][1],
                                                stream=True)
                if upstream is not None:
                    with upstream:
                        eof, _first = sse.pipe(upstream, self.wfile)
                        if not eof:
                            print('migration stream truncated',
                                  flush=True)
                    return None
            rows = [o[1] if o[0] == 'done' else None
                    for o in outcomes]
            for i, rec in recs:
                rows[i] = self._resume_stream_locally(i, rec)
            return rows

        def _resume_stream_locally(self, index, rec):
            """Local warm resume of one evacuated streaming row:
            resubmit the committed tokens (prefix-cache hit on the
            promoted pages) and keep streaming the NEW tokens under
            the row's original index. Returns the full row; a repeat
            evacuation or failure returns the committed row as-is
            (the stream truncates at the committed point, exactly
            like a replica death would)."""
            tokens = [int(t) for t in rec.get('tokens') or []]
            remaining = max(int(rec.get('limit', 0)) - len(tokens), 1)
            deadline_s = (float(rec.get('deadline_s') or 0)
                          or rt.request_timeout)
            t0 = time.monotonic()
            try:
                h = rt.submit_stream(
                    tokens, remaining,
                    rec.get('temperature', 0.0),
                    top_k=rec.get('top_k', 0),
                    top_p=rec.get('top_p', 1.0),
                    stop_token_ids=list(
                        rec.get('stop_token_ids') or []),
                    deadline_s=deadline_s,
                    adapter=rec.get('adapter'),
                    trace_ctx=getattr(self, '_trace_ctx', None))
            except Exception as e:  # pylint: disable=broad-except
                print(f'local stream resume failed to submit '
                      f'({type(e).__name__}: {e}); stream truncates '
                      f'at the committed point', flush=True)
                return tokens
            try:
                for _j, t in iter_interleaved([h]):
                    self.sse_send({'index': index, 'token': t})
                row = h.future.result(timeout=deadline_s + 30.0)
            except Exception as e:  # pylint: disable=broad-except
                print(f'local stream resume failed '
                      f'({type(e).__name__}: {e}); stream truncates '
                      f'at the committed point', flush=True)
                rt.cancel_streams([h])
                return tokens
            rt.record_migration('local_fallback',
                                time.monotonic() - t0, ok=True)
            return row

        def _openai_completions(self):
            try:
                body = self._read_body()
                prompts = body.get('prompt', '')
                if isinstance(prompts, str):
                    prompts = [prompts]
                req = oai.CompletionRequest(
                    prompts=prompts,
                    max_new=int(body.get('max_tokens', 16)),
                    temperature=float(body.get('temperature', 1.0)),
                    top_p=float(body.get('top_p', 1.0)),
                    stop_strings=body.get('stop') or [],
                    n=int(body.get('n', 1)),
                    stream=bool(body.get('stream')),
                    logprobs=body.get('logprobs'),
                    echo=bool(body.get('echo')),
                    deadline_s=rt.deadline_for(body),
                    adapter=rt.resolve_model(body.get('model')),
                    model=body.get('model'))
                try:
                    if req.stream:
                        oai.stream_completion(rt, req, self)
                    else:
                        self._json(oai.run_completion(rt, req))
                except SessionMigratedError:
                    # Evacuated mid-request: replay on the promoted
                    # warm pages (the prompt prefill is a prefix-cache
                    # hit). Mid-stream there is no replay — headers
                    # are out; _oai_error truncates the stream.
                    if getattr(self, '_sse_open', False):
                        raise
                    if req.stream:
                        oai.stream_completion(rt, req, self)
                    else:
                        self._json(oai.run_completion(rt, req))
            except Exception as e:  # pylint: disable=broad-except
                self._oai_error(e)

        def _openai_chat(self):
            try:
                body = self._read_body()
                # Model validation FIRST: an unknown model must 404
                # before prompt rendering can fail 400 on tokenizer
                # details.
                adapter = rt.resolve_model(body.get('model'))
                prompt = oai.render_chat_prompt(rt, body['messages'])
                # Modern chat knobs: logprobs is a bool +
                # top_logprobs count (clamped to the engine's 5).
                chat_lp = None
                if body.get('logprobs'):
                    chat_lp = min(int(body.get('top_logprobs', 0)), 5)
                req = oai.CompletionRequest(
                    prompts=[prompt],
                    max_new=int(body.get('max_tokens', 16)),
                    temperature=float(body.get('temperature', 1.0)),
                    top_p=float(body.get('top_p', 1.0)),
                    stop_strings=body.get('stop') or [],
                    n=int(body.get('n', 1)),
                    stream=bool(body.get('stream')),
                    logprobs=chat_lp,
                    deadline_s=rt.deadline_for(body),
                    adapter=adapter,
                    model=body.get('model'))
                try:
                    if req.stream:
                        oai.stream_completion(rt, req, self,
                                              chat=True)
                    else:
                        self._json(oai.to_chat_response(
                            oai.run_completion(rt, req)))
                except SessionMigratedError:
                    # Same warm-replay contract as /v1/completions.
                    if getattr(self, '_sse_open', False):
                        raise
                    if req.stream:
                        oai.stream_completion(rt, req, self,
                                              chat=True)
                    else:
                        self._json(oai.to_chat_response(
                            oai.run_completion(rt, req)))
            except Exception as e:  # pylint: disable=broad-except
                self._oai_error(e)

        def _oai_error(self, e: Exception):
            code, headers = self._robustness_accounting(e)
            if getattr(self, '_sse_open', False):
                # Headers already sent: the OpenAI stream contract has
                # no in-band error frame; close the stream.
                try:
                    self.sse_done()
                except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — closing an already-broken stream; client is gone
                    pass
                return
            err_type = {429: 'rate_limit_exceeded',
                        503: 'service_unavailable',
                        504: 'timeout'}.get(code,
                                            'invalid_request_error')
            err = {'message': f'{type(e).__name__}: {e}',
                   'type': err_type}
            if code == 404:
                # The OpenAI unknown-model error object.
                err['code'] = 'model_not_found'
            self._json({'error': err}, code, headers=headers)

        def _generate_text(self):
            try:
                tok = rt.get_tokenizer()
                req = self._read_body()
                prompts = req['prompts']
                if isinstance(prompts, str):
                    prompts = [prompts]
                temperature = float(req.get('temperature', 0.0))
                top_k = int(req.get('top_k', 0))
                top_p = float(req.get('top_p', 1.0))
                stop_strings = req.get('stop') or []
                if isinstance(stop_strings, str):
                    stop_strings = [stop_strings]
                max_new = int(req.get('max_new_tokens', 64))
                stream = bool(req.get('stream'))
                adapter = rt.resolve_model(req.get('model'))
                deadline_s = rt.deadline_for(req)
                encoded = [tok(p)['input_ids'] for p in prompts]
                limit = rt.limit_for(temperature, streaming=stream)
                for ids in encoded:
                    if len(ids) >= limit:
                        raise ValueError(
                            f'prompt tokenizes to {len(ids)} >= '
                            f'max_total_len {limit}')
                if stream:
                    self._generate_text_stream(
                        encoded, max_new, temperature, top_k, top_p,
                        stop_strings, deadline_s, adapter)
                    return
                t0 = time.monotonic()
                ttft = None
                eng = rt.engine_for(adapter)
                if eng is not None:
                    latch = obs_catalog.FirstTokenLatch()
                    futs = _submit_all(
                        eng, encoded, max_new_tokens=max_new,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, on_token=latch,
                        deadline_s=deadline_s, adapter=adapter,
                        trace_ctx=getattr(self, '_trace_ctx', None))
                    rows = []
                    for f in futs:
                        try:
                            rows.append(f.result(
                                timeout=deadline_s + 30.0))
                        except SessionMigratedError as me:
                            rows.append(self._resume_record(
                                me.record))
                    ttft = latch.first_token_s
                else:
                    rows = rt.one_shot_rows(encoded, max_new,
                                            temperature)
                texts = [tok.decode(row[len(ids):],
                                    skip_special_tokens=True)
                         for ids, row in zip(encoded, rows)]
                texts = [oai.trim_stops(t, stop_strings)[0]
                         for t in texts]
                n_gen = sum(len(r) - len(p)
                            for r, p in zip(rows, encoded))
                rt.metrics.record(time.monotonic() - t0, n_gen,
                                  ttft_s=ttft,
                                  n_prompt_tokens=sum(
                                      len(p) for p in encoded))
                self._json({'texts': texts})
            except Exception as e:  # pylint: disable=broad-except
                self._plain_error(e)

        def _generate_text_stream(self, encoded: List[List[int]],
                                  max_new, temperature, top_k, top_p,
                                  stop_strings, deadline_s,
                                  adapter=None):
            """SSE of {"index": i, "delta": text} events (incremental
            detokenization + stop-string holdback per row)."""
            tok = rt.get_tokenizer()
            t0 = time.monotonic()
            handles = [rt.submit_stream(
                ids, max_new, temperature, top_k=top_k, top_p=top_p,
                deadline_s=deadline_s, adapter=adapter,
                trace_ctx=getattr(self, '_trace_ctx', None))
                       for ids in encoded]
            self.sse_start()
            decs = [oai.IncrementalDecoder(tok) for _ in encoded]
            scans = [oai.StopStringScanner(stop_strings)
                     for _ in encoded]
            n_gen = 0
            ttft = None
            migrated = False
            try:
                try:
                    for i, t in iter_interleaved(handles):
                        if ttft is None:
                            ttft = time.monotonic() - t0
                        n_gen += 1
                        if scans[i].hit:
                            continue
                        out = scans[i].push(decs[i].push(t))
                        if out:
                            self.sse_send({'index': i, 'delta': out})
                except SessionMigratedError:
                    migrated = True
            finally:
                rt.cancel_streams(handles)  # no-op when completed
            if migrated:
                # Evacuated mid-stream: the committed deltas already
                # went out; finish each migrated row locally on the
                # promoted warm pages (text endpoints never ship —
                # the peer path is token-request only).
                for i, h in enumerate(handles):
                    try:
                        h.future.result(timeout=0.001)
                    except SessionMigratedError as me:
                        self._resume_text_stream_locally(
                            i, me.record, decs, scans)
                    except Exception as e:  # pylint: disable=broad-except
                        # Row failed for a non-migration reason: the
                        # stream truncates for it, like the pre-
                        # migration behavior.
                        print(f'text stream row {i} failed during '
                              f'evacuation ({type(e).__name__}: {e})',
                              flush=True)
            for i in range(len(handles)):
                if not scans[i].hit:
                    out = (scans[i].push(decs[i].flush()) +
                           scans[i].flush())
                    if out:
                        self.sse_send({'index': i, 'delta': out})
            self.sse_done()
            rt.metrics.record(time.monotonic() - t0, n_gen,
                              ttft_s=ttft,
                              n_prompt_tokens=sum(
                                  len(ids) for ids in encoded))

        def _resume_text_stream_locally(self, index, rec, decs,
                                        scans):
            """Local warm resume of one evacuated text-stream row:
            continuation tokens run through the row's incremental
            decoder + stop scanner so the delta stream picks up
            exactly where it left off."""
            tokens = [int(t) for t in rec.get('tokens') or []]
            remaining = max(int(rec.get('limit', 0)) - len(tokens), 1)
            deadline_s = (float(rec.get('deadline_s') or 0)
                          or rt.request_timeout)
            t0 = time.monotonic()
            try:
                h = rt.submit_stream(
                    tokens, remaining,
                    rec.get('temperature', 0.0),
                    top_k=rec.get('top_k', 0),
                    top_p=rec.get('top_p', 1.0),
                    deadline_s=deadline_s,
                    adapter=rec.get('adapter'),
                    trace_ctx=getattr(self, '_trace_ctx', None))
            except Exception as e:  # pylint: disable=broad-except
                print(f'local text-stream resume failed to submit '
                      f'({type(e).__name__}: {e}); row {index} '
                      f'truncates at the committed point', flush=True)
                return
            try:
                for _j, t in iter_interleaved([h]):
                    if scans[index].hit:
                        continue
                    out = scans[index].push(decs[index].push(t))
                    if out:
                        self.sse_send({'index': index, 'delta': out})
            except Exception as e:  # pylint: disable=broad-except
                print(f'local text-stream resume failed '
                      f'({type(e).__name__}: {e}); row {index} '
                      f'truncates at the committed point', flush=True)
                rt.cancel_streams([h])
                return
            rt.record_migration('local_fallback',
                                time.monotonic() - t0, ok=True)

    server = ThreadingHTTPServer(('0.0.0.0', port), Handler)
    server.inflight = _inflight            # type: ignore[attr-defined]
    server.inflight_lock = _inflight_lock  # type: ignore[attr-defined]
    server.draining = _draining            # type: ignore[attr-defined]
    return server


class ServePreemptionNotice(train_guard.PreemptionNotice):
    """Serving-side preemption watcher: the trainer's GCE-metadata
    poll + injectable notice (robustness/train_guard.py), firing the
    `serve.preempt_notice` fault point instead of the trainer's —
    zone-scoped drop rules are how decode_zone_storm.json preempts
    one spot pool without touching the rest of the fleet. SIGTERM
    stays with serve()'s own drain handler (install_sigterm=False),
    which evacuates too; this watcher covers the ~30s metadata notice
    that arrives BEFORE the SIGTERM on GCE spot VMs."""

    def trigger(self, reason: str) -> None:
        # Latch only: the train-plane notice counter stays a train
        # metric; serving preemptions are visible through the
        # migration counters the evacuation path ticks.
        if not self.notice.is_set():
            self.reason = reason
            self.notice.set()

    def _poll_loop(self) -> None:
        while not self._stop.is_set() and not self.notice.is_set():
            self.polls += 1
            if faults.point('serve.preempt_notice',
                            **self.ctx) is faults.DROP:
                self.trigger('injected')
                break
            if self._probe_metadata():
                self.trigger('metadata')
                break
            self._stop.wait(self.poll_interval_s)


def evacuate_for_exit(rt: InferenceRuntime,
                      reason: str = 'drain') -> dict:
    """Mass chain evacuation ahead of process exit (SIGTERM drain or
    preemption notice): every live engine's active sessions resolve
    with SessionMigratedError, and their owning HTTP threads ship the
    chains to peers / finish locally on the promoted pages. Failures
    are logged, never raised — a broken engine must not stop the
    drain from completing."""
    total = {'evacuated': 0, 'chains': 0, 'queued': 0}
    for eng in rt.live_engines():
        fn = getattr(eng, 'evacuate_chains', None)
        if fn is None:
            continue
        try:
            s = fn(reason=reason)
        except Exception as e:  # pylint: disable=broad-except
            print(f'evacuation failed on an engine '
                  f'({type(e).__name__}: {e}); its sessions finish '
                  f'locally', flush=True)
            continue
        for k in total:
            total[k] += int(s.get(k, 0))
    if total['evacuated'] or total['queued']:
        rt.record_evacuation(total)
        print(f'serve_lm: evacuated {total["evacuated"]} active + '
              f'{total["queued"]} queued sessions '
              f'({total["chains"]} KV chains packed, '
              f'reason={reason})', flush=True)
    return total


def drain(server: ThreadingHTTPServer, rt: InferenceRuntime,
          drain_grace: float, straggler_grace: float = 0.5,
          exit_fn=os._exit) -> None:
    """Graceful drain: flip /readyz to 503 (readiness probes pull the
    replica out of rotation), evacuate every active KV chain (the
    owning HTTP threads migrate the sessions to peers — in-flight
    POSTs the wait below covers — or finish them locally), let the
    accept loop pick up stragglers for `straggler_grace`, stop
    accepting, wait for in-flight POSTs (bounded by `drain_grace`),
    exit 0 — a mid-generation client must not see a reset because the
    controller culled this replica. `exit_fn` is injectable so the
    drain contract is testable without killing the test process."""
    server.draining.set()
    print('serve_lm: SIGTERM — draining in-flight requests',
          flush=True)
    # Drain-by-migration (idempotent: a controller that already
    # POSTed /kv/evacuate left the engines empty, and this finds
    # nothing). Failure falls back to the classic local-finish drain.
    evacuate_for_exit(rt, reason='drain')
    time.sleep(straggler_grace)  # stragglers: accept loop gets them
    server.shutdown()   # stops accepting; handlers keep running
    deadline = time.monotonic() + drain_grace
    while time.monotonic() < deadline:
        with server.inflight_lock:
            if server.inflight['n'] == 0:
                break
        time.sleep(0.05)
    rt.stop()
    # exit_fn defaults to os._exit: skip the XLA C++ teardown
    # entirely — destructor ordering under an in-flight device stream
    # SIGABRTs nondeterministically (the drain is complete; there is
    # nothing left to clean up).
    exit_fn(0)


def serve(rt: InferenceRuntime, port: int,
          drain_grace: float = 630.0, zone: str = '',
          watch_preemption: bool = True) -> None:
    """Run the HTTP server until killed. `drain_grace` bounds the
    SIGTERM drain wait; it defaults ABOVE the 600s request-timeout
    default so a worst-case in-flight generation still completes —
    requests longer than the grace window are dropped at exit.
    `zone` labels the replica's placement (spot decode pools) and
    scopes the preemption watcher's fault context; the watcher turns
    a GCE preemption notice — or an injected `serve.preempt_notice`
    drop — into mass chain evacuation followed by the normal drain,
    all inside the ~30s grace window."""
    server = make_server(rt, port)

    _term = threading.Event()

    def _drain_loop():
        """All drain work happens on this pre-started thread; the
        signal handler only sets an event (anything heavier in the
        signal frame proved crash-prone against the XLA runtime's own
        thread machinery)."""
        _term.wait()
        drain(server, rt, drain_grace)

    threading.Thread(target=_drain_loop, daemon=True).start()
    signal.signal(signal.SIGTERM, lambda *_: _term.set())
    if watch_preemption:
        ctx = {'zone': zone} if zone else {}
        notice = ServePreemptionNotice(poll_interval_s=2.0,
                                       install_sigterm=False,
                                       ctx=ctx)
        notice.start()

        def _preempt_watch():
            notice.notice.wait()
            print(f'serve_lm: preemption notice ({notice.reason}) — '
                  f'evacuating active sessions', flush=True)
            rt.set_evacuation_hint('preempt', None)
            evacuate_for_exit(rt, reason='preempt')
            _term.set()  # the drain loop finishes the exit

        threading.Thread(target=_preempt_watch, daemon=True).start()
    print(f'serve_lm listening on :{port} model={rt.model_name}',
          flush=True)
    server.serve_forever()
