"""Inference runtime: model loading, request execution, metrics.

Owns everything the HTTP layer needs to run a request: the model +
placed params, the per-(batch, temperature, length) one-shot jit
buckets, the optional continuous-batching engine, a streaming path
(engine token callbacks; a small lazy engine backs streaming when the
server runs in one-shot mode), and serving metrics (TTFT / e2e
latency percentiles surfaced by /stats — the BASELINE.md north-star
"p50 TTFT" is measured here).
"""
from __future__ import annotations

import collections
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple


class ServingMetrics:
    """Request metrics, thread-safe, dual-exported:

      - JSON `/stats` percentiles over a ROLLING WINDOW of the last
        `window` (default 1024) requests — `*_p50`/`*_p95` keys move
        as old requests age out;
      - Prometheus histograms/counters on `GET /metrics` covering the
        WHOLE process lifetime (observability/catalog.py).

    TTFT is the first COMMITTED token: streamed requests latch it at
    the first streamed token, non-streaming engine-backed requests at
    the first decode-step commit (catalog.FirstTokenLatch). One-shot
    (non-engine, non-streaming) requests have no per-token signal and
    record no TTFT. Inter-token gaps come from streamed requests
    only, measured per request row."""

    def __init__(self, window: int = 1024) -> None:
        from skypilot_tpu.observability import catalog as obs_catalog
        self._lock = threading.Lock()
        self.window = window
        self.ttft_ms: 'collections.deque' = collections.deque(
            maxlen=window)
        self.itl_ms: 'collections.deque' = collections.deque(
            maxlen=window)
        self.latency_ms: 'collections.deque' = collections.deque(
            maxlen=window)
        self.completion_tokens: 'collections.deque' = collections.deque(
            maxlen=window)
        self.requests = 0
        self.requests_shed = 0
        self.deadline_exceeded = 0
        self.prom = obs_catalog.RequestMetrics()
        # Declarative SLO accounting (observability/slo.py), attached
        # by build_runtime when --slo is set: every record()/
        # record_shed()/record_deadline_exceeded()/record_inter_token()
        # also feeds the burn-rate tracker. None = no SLO declared.
        self.slo = None

    def record(self, latency_s: float, n_tokens: int,
               ttft_s: Optional[float] = None,
               n_prompt_tokens: int = 0) -> None:
        with self._lock:
            self.requests += 1
            self.latency_ms.append(latency_s * 1000.0)
            self.completion_tokens.append(n_tokens)
            if ttft_s is not None:
                self.ttft_ms.append(ttft_s * 1000.0)
        self.prom.requests.inc()
        self.prom.e2e_latency_seconds.observe(latency_s)
        self.prom.completion_tokens.inc(max(n_tokens, 0))
        self.prom.prompt_tokens.inc(max(n_prompt_tokens, 0))
        if ttft_s is not None:
            self.prom.ttft_seconds.observe(ttft_s)
        if self.slo is not None:
            self.slo.record_request(
                ttft_ms=(ttft_s * 1000.0 if ttft_s is not None
                         else None))

    def record_shed(self) -> None:
        """One request rejected 429 by admission control."""
        with self._lock:
            self.requests_shed += 1
        self.prom.requests_shed.inc()
        if self.slo is not None:
            self.slo.record_request(shed=True)

    def record_deadline_exceeded(self) -> None:
        """One request answered 504 (expired queued or mid-decode)."""
        with self._lock:
            self.deadline_exceeded += 1
        self.prom.deadline_exceeded.inc()
        if self.slo is not None:
            self.slo.record_request(error=True)

    def record_inter_token(self, gap_s: float) -> None:
        """One gap between consecutive streamed tokens of a request
        row, measured at ENGINE COMMIT time (StreamHandle.on_token on
        the scheduler thread) — not at SSE frame delivery, which rides
        pump-thread scheduling and TCP flush batching and can inflate
        tail gaps by an order of magnitude under load."""
        with self._lock:
            self.itl_ms.append(gap_s * 1000.0)
        self.prom.inter_token_seconds.observe(gap_s)
        if self.slo is not None:
            self.slo.record_itl(gap_s * 1000.0)

    @staticmethod
    def _pct(values: List[float], q: float) -> Optional[float]:
        """Linear-interpolated percentile (numpy's default method).
        Nearest-rank reporting at small N made distinct percentiles
        collapse onto the same sample (p95 == p99 with 60 requests),
        which misreads as a flat tail; interpolation keeps them
        distinct and converges to the same values at large N."""
        if not values:
            return None
        s = sorted(values)
        pos = q * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return round(s[lo] * (1.0 - frac) + s[hi] * frac, 2)

    def snapshot(self) -> Dict[str, object]:
        """JSON stats. Window semantics: every `*_p50`/`*_p95` key and
        `gen_tokens_per_sec` cover the last `window` requests (see
        `window` key); `requests` counts the process lifetime. TTFT
        keys cover streamed + engine-backed non-streaming requests;
        `itl_ms_*` cover streamed requests only."""
        with self._lock:
            lat = list(self.latency_ms)
            ttft = list(self.ttft_ms)
            itl = list(self.itl_ms)
            toks = list(self.completion_tokens)
            n = self.requests
            shed = self.requests_shed
            expired = self.deadline_exceeded
        total_s = sum(lat) / 1000.0
        return {
            'requests': n,
            'requests_shed': shed,
            'deadline_exceeded': expired,
            'window': self.window,
            # Sample counts per latency block: percentiles over a
            # handful of samples are noise — consumers (benches,
            # dashboards) can qualify them.
            'ttft_ms_n': len(ttft),
            'itl_ms_n': len(itl),
            'latency_ms_n': len(lat),
            'ttft_ms_p50': self._pct(ttft, 0.50),
            'ttft_ms_p95': self._pct(ttft, 0.95),
            'ttft_ms_p99': self._pct(ttft, 0.99),
            'itl_ms_p50': self._pct(itl, 0.50),
            'itl_ms_p95': self._pct(itl, 0.95),
            'itl_ms_p99': self._pct(itl, 0.99),
            'latency_ms_p50': self._pct(lat, 0.50),
            'latency_ms_p95': self._pct(lat, 0.95),
            'completion_tokens_total': sum(toks),
            'gen_tokens_per_sec': round(sum(toks) / total_s, 2)
            if total_s > 0 else None,
        }


class StreamHandle:
    """Consumer side of one streaming request: committed tokens arrive
    on `q` (pushed from the engine scheduler thread); `future` resolves
    to the full prompt++generated list when the request finishes.
    `first_token_s` latches the TTFT instant and consecutive commits
    record inter-token gaps (the serving ITL signal, measured at the
    commit itself rather than at SSE delivery). Constructed BEFORE the
    engine submit so the very first committed token always finds the
    queue (the scheduler thread races the submitting thread)."""

    def __init__(self, metrics: Optional[ServingMetrics] = None
                 ) -> None:
        self.q: 'queue.Queue' = queue.Queue()
        self.future: Optional['Future'] = None  # set right after submit
        self.t0 = time.monotonic()
        self.first_token_s: Optional[float] = None
        self._metrics = metrics
        self._last_token_t: Optional[float] = None

    def on_token(self, tok: int) -> None:
        now = time.monotonic()
        if self.first_token_s is None:
            self.first_token_s = now - self.t0
        elif self._metrics is not None:
            self._metrics.record_inter_token(now - self._last_token_t)
        self._last_token_t = now
        self.q.put(tok)


def iter_interleaved(handles: List[StreamHandle]):
    """Yield (choice_index, token) across streams in arrival order
    until every stream completes — one slow choice must not stall its
    siblings' chunks. Re-raises the engine's exception on failure.
    The shared poll loop behind every SSE endpoint (done-detection
    order matters: Empty -> future.done() -> q.empty() re-check closes
    the commit/resolve race window)."""
    done = [False] * len(handles)
    while not all(done):
        progressed = False
        for i, h in enumerate(handles):
            if done[i]:
                continue
            try:
                tok = h.q.get_nowait()
            except queue.Empty:
                if h.future.done() and h.q.empty():
                    h.future.result()  # raise to the caller on error
                    done[i] = True
                    progressed = True
                continue
            progressed = True
            yield i, int(tok)
        if not progressed:
            time.sleep(0.005)


class InferenceRuntime:
    """Everything needed to execute generation requests.

    `engine` is the continuous-batching engine when the server runs in
    that mode, else None; `stream_engine()` always returns an engine
    (lazily building a small one in one-shot mode) because streaming
    needs per-token commit callbacks, which only the slot engine has.
    """

    def __init__(self, *, model, params, vocab_size: int,
                 model_name: str, max_total_len: int, spec_total: int,
                 speculative: int, engine=None,
                 engine_total: Optional[int] = None,
                 tokenizer_dir: Optional[str] = None,
                 stream_slots: int = 2,
                 prefill_chunk: int = 0,
                 prefill_budget: int = 0,
                 pipeline_decode: Optional[bool] = None,
                 request_timeout: float = 600.0,
                 max_queue_requests: int = 0,
                 max_queue_tokens: int = 0,
                 adapters=None,
                 kv_dtype: str = 'bf16',
                 weight_dtype: str = 'bf16',
                 role: str = '',
                 decode_peers: Optional[List[str]] = None,
                 mesh=None) -> None:
        import jax
        self.model = model
        self.params = params
        # Tensor-parallel serving mesh (None = single device): the
        # engines' KV pools shard over it; /stats `storage` reports
        # mesh_devices so operators can audit per-chip pool math.
        self.mesh = mesh
        self.mesh_devices = (int(mesh.devices.size)
                             if mesh is not None else 1)
        # Pipeline-parallel stage count (--stages; 1 = no split):
        # /stats `storage.stages` alongside mesh_devices, so
        # tensor_ways = mesh_devices / stages.
        self.stages = (int(mesh.shape.get('stage', 1))
                       if mesh is not None else 1)
        # Disaggregated serving (docs/guides.md "Disaggregated
        # serving & cache tiering"): '' = unified replica (the
        # classic mode), 'decode' labels a decode-pool member,
        # 'prefill' additionally hands finished prompts' KV page
        # chains off to a decode peer instead of decoding locally.
        if role not in ('', 'unified', 'prefill', 'decode'):
            raise ValueError(f'unknown serving role {role!r}')
        self.role = '' if role == 'unified' else role
        self._peers_lock = threading.Lock()
        self._decode_peers: List[str] = []
        self._peer_ring = None
        self._handoff_lock = threading.Lock()
        self.handoffs_total = 0
        self.handoff_failures = 0
        self.handoff_bytes_total = 0
        self.kv_imports_total = 0
        self.kv_imported_pages_total = 0
        from skypilot_tpu.observability import catalog as _obs
        self._handoff_seconds = _obs.histogram(
            'skypilot_serving_kv_handoff_seconds')
        self._handoff_bytes = _obs.counter(
            'skypilot_serving_kv_handoff_bytes_total')
        # Live KV-chain migration (PR 20): out-migration counts by
        # trigger reason, evacuation totals, and the bounded ring of
        # affinity keys migrated IN — /stats exposes the ring so the
        # fleet controller can pin those sessions' follow-ups to this
        # replica at the LB.
        self._migration_lock = threading.Lock()
        self.migrations_by_reason: Dict[str, int] = {}
        self.migration_failures = 0
        self.sessions_evacuated_total = 0
        self.chains_evacuated_total = 0
        self.tokens_recomputed_total = 0
        self.migrations_in_total = 0
        self._migrated_in_keys: 'collections.OrderedDict[str, None]' \
            = collections.OrderedDict()
        # Evacuation hint: set by /kv/evacuate (controller-supplied
        # target + reason), read by the HTTP threads whose futures
        # resolve with SessionMigratedError moments later. Expires so
        # a stale rebalance hint can't redirect a later drain.
        self._evac_hint: Optional[Dict[str, object]] = None
        self._migration_seconds = _obs.histogram(
            'skypilot_serving_migration_seconds')
        self._chains_evacuated = _obs.counter(
            'skypilot_serving_chains_evacuated_total')
        self._tokens_recomputed = _obs.counter(
            'skypilot_serving_tokens_recomputed_total')
        if decode_peers:
            self.set_decode_peers(decode_peers)
        # Quantized-serving storage formats (inference/quant.py +
        # the model config's kv_dtype) — /stats and the
        # skypilot_serving_storage_info series report them.
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        from skypilot_tpu.inference import quant as quant_lib
        self.weight_bytes = quant_lib.weight_num_bytes(params)
        # Multi-LoRA adapter registry (inference/adapters.py) shared
        # by every engine in this runtime; None = base model only.
        self.adapters = adapters
        self.vocab_size = vocab_size
        self.model_name = model_name
        self.max_total_len = max_total_len
        self.spec_total = spec_total
        self.speculative = speculative
        self.engine = engine
        # engine_total overrides when the constructed engine's
        # capacity differs from the derived default (decode-chunk
        # clamp) — limit_for/advertised capacity must match what
        # engine.submit actually accepts.
        self.engine_total = engine_total if engine_total is not None \
            else (spec_total if speculative > 0 else max_total_len)
        self.tokenizer_dir = tokenizer_dir
        self.metrics = ServingMetrics()
        # Declared serving SLO (observability/slo.py), attached by
        # build_runtime when --slo is set; /stats renders its
        # burn-rate snapshot. None = no SLO declared.
        self.slo_tracker = None

        self._fns: Dict[Tuple[int, float, int], object] = {}
        self._lock = threading.Lock()
        self._rng = jax.random.PRNGKey(0)
        self._tok_holder: Dict[str, object] = {}
        self._tok_lock = threading.Lock()
        self._stream_engine = None
        self._stream_engine_lock = threading.Lock()
        self._stream_slots = stream_slots
        # Stall-free-scheduler knobs, reused by the lazy stream
        # engine so one-shot-mode streaming gets the same behavior.
        self._prefill_chunk = prefill_chunk
        self._prefill_budget = prefill_budget
        self._pipeline_decode = pipeline_decode
        # Robustness knobs: the server-wide request-deadline ceiling
        # (per-request `timeout` fields clamp to it) and the bounded
        # queue the lazy stream engine shares with the main one.
        self.request_timeout = float(request_timeout)
        self._max_queue_requests = max_queue_requests
        self._max_queue_tokens = max_queue_tokens

    # -- capacity -----------------------------------------------------------
    def limit_for(self, temperature: float,
                  streaming: bool = False) -> int:
        """Max total length the request class will actually run at.
        Streaming always runs through a slot engine built at
        engine_total — validate against THAT capacity, not the
        one-shot bucket's (they differ in one-shot+speculative mode)."""
        if self.engine is not None or streaming:
            return self.engine_total
        if self.speculative > 0 and temperature == 0.0:
            return self.spec_total
        return self.max_total_len

    # -- disaggregated prefill/decode ---------------------------------------
    def set_decode_peers(self, peers: List[str]) -> None:
        """Install the decode pool this prefill replica hands off to
        (endpoint strings 'host:port'). Pushed by the fleet
        controller via POST /kv/peers whenever the decode ready set
        changes; also settable statically with --decode-peers. The
        peer ring is the SAME consistent-hash mapping the LB's
        prefix-affinity policy uses over the same endpoint strings,
        so a handed-off session's follow-up requests (routed by the
        LB directly to the decode pool) land on the replica that
        already holds the imported pages."""
        from skypilot_tpu.serve import \
            load_balancing_policies as lb_policies
        peers = list(dict.fromkeys(str(p) for p in peers if p))
        ring = None
        if peers:
            ring = lb_policies.PrefixAffinityPolicy()
            ring.set_ready_replicas(peers)
        with self._peers_lock:
            self._decode_peers = peers
            self._peer_ring = ring

    def decode_peers(self) -> List[str]:
        with self._peers_lock:
            return list(self._decode_peers)

    def pick_decode_peer(self, key: Optional[str]) -> Optional[str]:
        """Handoff target for an affinity key: the ring's owner (the
        replica the LB would also pick for this session), else the
        first peer for keyless prompts."""
        with self._peers_lock:
            peers = list(self._decode_peers)
            ring = self._peer_ring
        if not peers:
            return None
        if key is not None and ring is not None:
            target = ring.affinity_target(key)
            if target is not None:
                return target
        return peers[0]

    def record_handoff(self, seconds: float, nbytes: int,
                       ok: bool) -> None:
        with self._handoff_lock:
            self.handoffs_total += 1
            if not ok:
                self.handoff_failures += 1
            self.handoff_bytes_total += nbytes
        self._handoff_seconds.observe(seconds)
        if nbytes:
            self._handoff_bytes.inc(nbytes)

    def record_kv_import(self, summary: Dict[str, int]) -> None:
        with self._handoff_lock:
            self.kv_imports_total += 1
            self.kv_imported_pages_total += int(
                summary.get('imported', 0))

    def handoff_stats(self) -> Dict[str, object]:
        with self._handoff_lock:
            return {
                'decode_peers': self.decode_peers(),
                'handoffs': self.handoffs_total,
                'failures': self.handoff_failures,
                'bytes': self.handoff_bytes_total,
                'kv_imports': self.kv_imports_total,
                'kv_imported_pages': self.kv_imported_pages_total,
            }

    # -- live KV-chain migration --------------------------------------------
    #: migrated-in affinity keys retained for controller pinning
    _MIGRATED_KEYS_MAX = 1024
    #: how long an evacuation hint stays actionable
    _EVAC_HINT_TTL_S = 30.0

    def set_evacuation_hint(self, reason: str,
                            target: Optional[str]) -> None:
        """Remember why the engine is about to evacuate (and where the
        controller wants the chains to go). Read by the HTTP threads
        whose futures resolve with SessionMigratedError; expires after
        a grace-window's worth of seconds so a stale rebalance target
        cannot redirect a later drain."""
        with self._migration_lock:
            self._evac_hint = {'reason': str(reason or 'drain'),
                               'target': target or None,
                               'expires': time.monotonic() +
                               self._EVAC_HINT_TTL_S}

    def evacuation_hint(self) -> Tuple[str, Optional[str]]:
        """(reason, target) of the live evacuation hint; defaults to
        ('drain', None) — ring-chosen target — when none is set."""
        with self._migration_lock:
            hint = self._evac_hint
            if hint and time.monotonic() < float(hint['expires']):
                return str(hint['reason']), hint['target']  # type: ignore[return-value]
        return 'drain', None

    def record_evacuation(self, summary: Dict[str, int]) -> None:
        """Account one engine evacuate_chains() result."""
        n_sessions = int(summary.get('evacuated', 0)) + \
            int(summary.get('queued', 0))
        n_chains = int(summary.get('chains', 0))
        with self._migration_lock:
            self.sessions_evacuated_total += n_sessions
            self.chains_evacuated_total += n_chains
        if n_chains:
            self._chains_evacuated.inc(n_chains)

    def record_migration(self, reason: str, seconds: float,
                         ok: bool) -> None:
        """Account one out-migration attempt (chain POST + tail
        proxy). Failed ships count under their own reason AND bump
        migration_failures; the session then finishes locally and the
        fallback is recorded separately as 'local_fallback'."""
        from skypilot_tpu.observability import catalog as _obs
        with self._migration_lock:
            self.migrations_by_reason[reason] = \
                self.migrations_by_reason.get(reason, 0) + 1
            if not ok:
                self.migration_failures += 1
        if ok:
            _obs.counter('skypilot_serving_migrations_total').labels(
                reason=reason).inc()
        self._migration_seconds.observe(seconds)

    def record_migrated_in(self, affinity_key: Optional[str],
                           tokens_recomputed: int) -> None:
        """Account one migrated-in session on the receiving side: the
        re-prefill cost (committed tokens not covered by imported
        pages) and the session's affinity key, kept in a bounded ring
        /stats exposes for LB pinning."""
        with self._migration_lock:
            self.migrations_in_total += 1
            self.tokens_recomputed_total += int(tokens_recomputed)
            if affinity_key:
                self._migrated_in_keys.pop(affinity_key, None)
                self._migrated_in_keys[affinity_key] = None
                while len(self._migrated_in_keys) > \
                        self._MIGRATED_KEYS_MAX:
                    self._migrated_in_keys.popitem(last=False)
        if tokens_recomputed:
            self._tokens_recomputed.inc(int(tokens_recomputed))

    def migration_stats(self) -> Dict[str, object]:
        with self._migration_lock:
            return {
                'migrations': dict(self.migrations_by_reason),
                'failures': self.migration_failures,
                'sessions_evacuated': self.sessions_evacuated_total,
                'chains_evacuated': self.chains_evacuated_total,
                'migrations_in': self.migrations_in_total,
                'tokens_recomputed': self.tokens_recomputed_total,
                'migrated_in_keys': list(self._migrated_in_keys),
            }

    # -- model / adapter resolution -----------------------------------------
    def resolve_model(self, model_field) -> Optional[str]:
        """Map a request's `model` field to an adapter name (None =
        the base model). The OpenAI 404 contract is honored even with
        no adapters configured: an unknown model raises
        AdapterNotFoundError instead of being silently served by the
        base model (the pre-LoRA behavior)."""
        if model_field is None or model_field == '':
            return None
        name = str(model_field)
        if name in (self.model_name, 'base', 'default'):
            return None
        if self.adapters is not None and self.adapters.exists(name):
            return name
        from skypilot_tpu.robustness.errors import AdapterNotFoundError
        known = ([self.model_name] +
                 (self.adapters.inventory()
                  if self.adapters is not None else []))
        raise AdapterNotFoundError(
            f'model {name!r} does not exist (known models: {known})')

    def engine_for(self, adapter: Optional[str] = None):
        """Engine that can run this request: the main engine, or —
        for adapter requests in one-shot mode — the lazy stream
        engine (the one-shot jit buckets have no per-slot LoRA
        path). None = use the one-shot path."""
        if self.engine is not None:
            return self.engine
        if adapter is not None:
            return self.stream_engine()
        return None

    # -- tokenizer ----------------------------------------------------------
    def get_tokenizer(self):
        with self._tok_lock:
            if 'tok' not in self._tok_holder:
                if self.tokenizer_dir is None:
                    raise ValueError(
                        'no tokenizer available: text endpoints need '
                        'a --hf checkpoint with tokenizer files; use '
                        '/generate with token ids instead')
                from skypilot_tpu.models.hf_import import load_tokenizer
                self._tok_holder['tok'] = load_tokenizer(
                    self.tokenizer_dir)
            return self._tok_holder['tok']

    # -- one-shot path ------------------------------------------------------
    def get_fn(self, batch: int, temperature: float, total: int = 0):
        """One jitted fn per (batch, temperature, total-length) bucket.
        `total` defaults to the engine's full capacity; text endpoints
        pass a smaller bucket so a 4-token completion does not pay for
        a full-buffer decode scan."""
        from skypilot_tpu.models import generate as gen
        if total <= 0:
            total = self.limit_for(temperature)
        key = (batch, temperature, total)
        with self._lock:
            if key not in self._fns:
                if self.speculative > 0 and temperature == 0.0:
                    self._fns[key] = gen.make_speculative_generate_fn(
                        self.model, total, draft_k=self.speculative)
                else:
                    self._fns[key] = gen.make_generate_fn(
                        self.model, total, temperature=temperature)
            return self._fns[key]

    def split_rng(self):
        import jax
        with self._lock:
            self._rng, sub = jax.random.split(self._rng)
        return sub

    def _score_fn(self, bucket: int):
        """Jitted full-sequence log-softmax over a padded bucket
        (teacher-forced scoring — the /v1/completions logprobs/echo
        contract eval harnesses drive)."""
        import jax
        import jax.numpy as jnp
        key = ('score', bucket)
        with self._lock:
            if key not in self._fns:
                model = self.model

                @jax.jit
                def score(params, tokens):
                    logits = model.apply({'params': params}, tokens)
                    return jax.nn.log_softmax(
                        logits.astype(jnp.float32), axis=-1)

                self._fns[key] = score
            return self._fns[key]

    def score_logprobs(self, ids: List[int]):
        """log P(token_i | tokens_<i) for the whole row: returns a
        [len(ids), vocab] numpy array of log-probs (row i scores
        position i+1's candidates)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        bucket = 8
        while bucket < len(ids):
            bucket *= 2
        bucket = min(bucket, self.max_total_len)
        fn = self._score_fn(bucket)
        padded = list(ids) + [0] * (bucket - len(ids))
        lp = fn(self.params, jnp.asarray([padded], jnp.int32))
        return np.asarray(jax.device_get(lp))[0, :len(ids)]

    def one_shot_rows(self, rows: List[List[int]], max_new: int,
                      temperature: float) -> List[List[int]]:
        """Run ragged rows through power-of-two one-shot buckets and
        return each row trimmed to prompt + max_new. Rows sharing a
        bucket could batch; they arrive per-request here, so each runs
        alone (the continuous engine is the batching mode)."""
        import jax
        import jax.numpy as jnp
        limit = self.limit_for(temperature)
        out_rows = []
        for ids in rows:
            want = len(ids) + max_new
            bucket = 8
            while bucket < want:
                bucket *= 2
            bucket = min(bucket, limit)
            fn = self.get_fn(1, temperature, bucket)
            out = fn(self.params, jnp.asarray([ids], jnp.int32),
                     self.split_rng())
            out_rows.append(
                jax.device_get(out)[0][:min(want, bucket)].tolist())
        return out_rows

    # -- streaming path -----------------------------------------------------
    def stream_engine(self):
        """The engine that backs streaming requests: the main engine
        in continuous mode; else a small lazily-built one (shares
        params — HBM cost is its slot KV cache only)."""
        if self.engine is not None:
            return self.engine
        with self._stream_engine_lock:
            if self._stream_engine is None:
                from skypilot_tpu.models.batching import \
                    ContinuousBatchingEngine
                self._stream_engine = ContinuousBatchingEngine(
                    self.model, self.params,
                    num_slots=self._stream_slots,
                    max_total_len=self.engine_total,
                    speculative_k=self.speculative,
                    prefill_chunk=self._prefill_chunk,
                    prefill_budget=self._prefill_budget,
                    pipeline_decode=(None if self.speculative
                                     else self._pipeline_decode),
                    max_queue_requests=self._max_queue_requests,
                    max_queue_tokens=self._max_queue_tokens,
                    adapter_store=self.adapters,
                    mesh=self.mesh)
            return self._stream_engine

    def deadline_for(self, req: dict) -> float:
        """Effective per-request deadline, seconds: the request's own
        `timeout` field clamped into (0, --request-timeout]."""
        try:
            t = float(req.get('timeout', self.request_timeout))
        except (TypeError, ValueError) as e:
            raise ValueError(f'invalid timeout field: {e}') from e
        if t <= 0:
            raise ValueError(f'timeout must be > 0, got {t}')
        return min(t, self.request_timeout)

    def submit_stream(self, ids: List[int], max_new: int,
                      temperature: float, top_k: int = 0,
                      top_p: float = 1.0,
                      stop_token_ids: Optional[List[int]] = None,
                      deadline_s: Optional[float] = None,
                      adapter: Optional[str] = None,
                      trace_ctx: Optional[object] = None
                      ) -> StreamHandle:
        eng = self.stream_engine()
        # Queue must exist before submit; commit-time ITL recording
        # rides the same callback.
        handle = StreamHandle(metrics=self.metrics)
        handle.future = eng.submit(
            ids, max_new_tokens=max_new, temperature=temperature,
            top_k=top_k, top_p=top_p, stop_token_ids=stop_token_ids,
            on_token=handle.on_token,
            deadline_s=(self.request_timeout if deadline_s is None
                        else deadline_s),
            adapter=adapter, trace_ctx=trace_ctx)
        return handle

    def live_engines(self) -> List[object]:
        """Engines constructed so far (main and/or lazy stream engine)
        — the scrape handlers refresh each one's gauges."""
        return [e for e in (self.engine, self._stream_engine)
                if e is not None]

    def cancel_streams(self, handles: List[StreamHandle]) -> None:
        """Abandon streamed requests whose consumer disconnected: the
        engine frees their slots instead of generating unread tokens.
        No-op for handles that already completed."""
        futs = [h.future for h in handles
                if h.future is not None and not h.future.done()]
        if not futs:
            return
        eng = self.engine if self.engine is not None \
            else self._stream_engine
        if eng is not None:
            eng.cancel(futs)

    def stop(self) -> None:
        if self.engine is not None:
            self.engine.stop()
        if self._stream_engine is not None:
            self._stream_engine.stop()


def build_runtime(args) -> InferenceRuntime:
    """Construct the runtime from serve_lm CLI args: load the model
    (registry or HF checkpoint), place params (TP-sharded over the
    mesh or single-device, bf16 by default), restore a checkpoint if
    given, and build the continuous engine when enabled."""
    import flax.linen as nn
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from skypilot_tpu.recipes.train_lm import _build_model

    tokenizer_dir = None
    hf_params = None
    serve_cast = None
    if args.hf:
        from skypilot_tpu.models import hf_import
        model, hf_params = hf_import.load_hf_checkpoint(
            args.hf, max_seq_len=args.max_total_len)
        # Raw f32 numpy here; the cast (bf16 via ml_dtypes) happens
        # PER LEAF at placement time below — host transient is one
        # leaf, device footprint is the bf16 shards.
        import ml_dtypes
        import numpy as _np
        serve_cast = (ml_dtypes.bfloat16 if args.param_dtype == 'bf16'
                      else _np.float32)
        vocab_size = model.config.vocab_size
        print(f'loaded HF checkpoint from {args.hf} '
              f'({type(model).__name__}, vocab={vocab_size})',
              flush=True)
        if any(os.path.exists(os.path.join(args.hf, f))
               for f in ('tokenizer.json', 'tokenizer_config.json',
                         'tokenizer.model')):
            tokenizer_dir = args.hf
    else:
        model, vocab_size, _ = _build_model(args.model,
                                            args.max_total_len,
                                            remat=False)

    # Quantized serving knobs (inference/quant.py): KV page storage
    # format + pool sizing in BYTES (so bf16/int8 A/B runs spend the
    # same HBM — int8 buys ~2x the pages), and int8 projection
    # weights below.
    from skypilot_tpu.inference import quant as quant_lib
    kv_dtype = getattr(args, 'kv_dtype', 'bf16') or 'bf16'
    weight_dtype = getattr(args, 'weight_dtype', 'bf16') or 'bf16'
    kv_pool_bytes = int(getattr(args, 'kv_pool_bytes', 0) or 0)
    if kv_dtype != 'bf16' or kv_pool_bytes:
        cfg = model.config
        if getattr(cfg, 'kv_dtype', None) is None or \
                getattr(cfg, 'kv_total_pages', 0) <= 0:
            raise SystemExit(
                f'--kv-dtype/--kv-pool-bytes need a paged-KV model '
                f'config with a kv_dtype field (the Llama family); '
                f'{type(cfg).__name__} has none')
        if kv_dtype == 'int8' and not args.continuous_batching:
            raise SystemExit(
                '--kv-dtype int8 requires --continuous-batching: the '
                'one-shot engine decodes through the dense per-slot '
                'cache, which has no scale storage')
        import dataclasses
        # --kv-pool-bytes is PER-CHIP HBM: under --tensor the pool's
        # kv-heads axis shards (parallel/serving.py GQA remainder
        # rule), one page costs 1/shard_ways the value bytes per
        # chip, and the same per-chip budget buys ~shard_ways x the
        # pages — an N-chip mesh holds ~N x the decode capacity at
        # fixed per-chip memory.
        from skypilot_tpu.parallel.serving import kv_shard_ways
        shard_ways = kv_shard_ways(
            int(getattr(cfg, 'num_kv_heads', 0) or 0),
            int(getattr(args, 'tensor', 1) or 1))
        # Under --stages each stage's pool stores only its own
        # [lo, hi) layer range, so a page costs ~1/S the bytes per
        # chip ON TOP of the tensor split — the same per-chip budget
        # buys ~S*shard_ways x the pages.
        stages = int(getattr(args, 'stages', 1) or 1)
        pages = (quant_lib.pool_pages_for_bytes(cfg, kv_dtype,
                                                kv_pool_bytes,
                                                shard_ways,
                                                stages=stages)
                 if kv_pool_bytes else cfg.kv_total_pages)
        cfg = dataclasses.replace(cfg, kv_dtype=kv_dtype,
                                  kv_total_pages=pages)
        model = type(model)(cfg)
        sharded = (f', kv heads sharded {shard_ways}-way'
                   if shard_ways > 1 else '')
        staged = (f', split over {stages} stages' if stages > 1
                  else '')
        print(f'kv cache: dtype={kv_dtype} pages={pages} '
              f'({quant_lib.kv_page_bytes(cfg, kv_dtype, shard_ways, stages=stages)} '
              f'bytes/page/chip across layers{sharded}{staged})',
              flush=True)

    # Speculative decoding writes its verify chunk up to K tokens past
    # the last kept one; fail fast / clamp at STARTUP instead of
    # erroring inside every request handler.
    spec_total = args.max_total_len
    if args.speculative > 0:
        spec_total = min(args.max_total_len,
                         model.config.max_seq_len - args.speculative)
        if spec_total <= 1:
            raise SystemExit(
                f'--speculative {args.speculative} needs headroom in '
                f'the model context: max_seq_len='
                f'{model.config.max_seq_len} leaves no room for the '
                f'verify chunk. Use a smaller K or a longer-context '
                f'model.')
        if spec_total < args.max_total_len:
            print(f'speculative decoding: clamping max_total_len '
                  f'{args.max_total_len} -> {spec_total} (verify chunk '
                  f'needs K={args.speculative} tokens of headroom '
                  f'below max_seq_len={model.config.max_seq_len})',
                  flush=True)

    if hf_params is not None:
        params = hf_params
    else:
        params = nn.meta.unbox(model.init(
            jax.random.PRNGKey(0),
            jnp.ones((1, 8), jnp.int32))['params'])
    # int8 projection weights: quantize HOST-SIDE from the f32/bf16
    # tree, then wrap the model so every jitted serving fn
    # dequantizes on read (inference/quant.py).
    if weight_dtype == 'int8':
        if args.ckpt_dir:
            raise SystemExit(
                '--weight-dtype int8 does not compose with '
                '--ckpt-dir (the restore template predates '
                'quantization); restore bf16 or convert first')
        qparams = quant_lib.quantize_params(params)
        if not quant_lib.is_quantized(qparams):
            raise SystemExit(
                f'--weight-dtype int8 found no quantizable '
                f'projection kernels ({quant_lib.WEIGHT_TARGETS}) in '
                f'this model; the Llama family is supported')
        params = qparams
        model = quant_lib.QuantizedModel(model)
        print('weights: int8 per-output-channel projections '
              '(dequant-on-read)', flush=True)
    elif weight_dtype != 'bf16':
        raise SystemExit(f'unsupported --weight-dtype {weight_dtype}')
    # ONE placement block for both param sources: TP-shard over the
    # mesh (per-leaf cast, shard-only transfers), stage×tensor split,
    # or single-device.
    mesh = None
    num_stages = int(getattr(args, 'stages', 1) or 1)
    if num_stages > 1:
        if weight_dtype == 'int8':
            raise SystemExit(
                '--stages does not compose with --weight-dtype int8 '
                '(the quantized wrapper has no per-stage split)')
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.parallel.serving import build_staged_serving
        mesh = mesh_lib.make_mesh(
            mesh_lib.MeshConfig(stage=num_stages, tensor=args.tensor),
            devices=jax.devices()[:num_stages * args.tensor])
        # Place per stage HERE (per-leaf cast, shard-only transfers
        # onto each stage's tensor submesh) and hand the engine the
        # re-merged tree: stage key sets are disjoint top-level
        # partitions, so the engine's own build_staged_serving split
        # re-places each already-resident leaf as a no-op.
        _, stage_params, _, _ = build_staged_serving(
            model, params, mesh, dtype=serve_cast)
        params = {}
        for sp in stage_params:
            params.update(sp)
        print(f'pipeline serving: {num_stages} stages x '
              f'{args.tensor}-way tensor over '
              f'{num_stages * args.tensor} devices', flush=True)
    elif args.tensor > 1:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.make_mesh(
            mesh_lib.MeshConfig(tensor=args.tensor),
            devices=jax.devices()[:args.tensor])
        if weight_dtype == 'int8':
            params = quant_lib.shard_quantized_for_serving(
                model, params, mesh, dtype=serve_cast)
        else:
            from skypilot_tpu.parallel.serving import \
                shard_params_for_serving
            params = shard_params_for_serving(model, params, mesh,
                                              dtype=serve_cast)
        print(f'tensor-parallel serving over {args.tensor} devices',
              flush=True)
    elif weight_dtype == 'int8':
        # Quantized leaves keep their int8/f32 dtypes; serve_cast
        # applies to the surviving dense leaves (embeddings, norms,
        # head) exactly as the bf16 path does.
        import numpy as _np

        def _place(x):
            x = _np.asarray(x)
            if serve_cast is not None and x.dtype == _np.float32 \
                    and x.ndim > 1:
                x = x.astype(serve_cast)
            return jnp.asarray(x)

        params = jax.tree.map(_place, params)
    elif serve_cast is not None:
        import numpy as _np
        params = jax.tree.map(
            lambda x: jnp.asarray(_np.asarray(x).astype(serve_cast)),
            params)
    if args.ckpt_dir:
        from skypilot_tpu.parallel.checkpoints import CheckpointManager
        mgr = CheckpointManager(args.ckpt_dir)
        if mgr.latest_step() is not None:
            from skypilot_tpu.parallel.train import TrainState
            import optax
            template = TrainState.create(params, optax.sgd(1e-3))
            params = mgr.restore(template).params
            print(f'loaded checkpoint step {mgr.latest_step()}',
                  flush=True)

    # Multi-LoRA adapter registry (serve_lm --adapter-dir): scanned
    # at startup, hot-loaded on demand; every engine in the process
    # shares the one device store.
    adapters = None
    adapter_dir = getattr(args, 'adapter_dir', None)
    if adapter_dir:
        from skypilot_tpu.inference.adapters import AdapterRegistry
        adapters = AdapterRegistry(
            adapter_dir, model,
            # Staged engines keep the adapter stacks UNCOMMITTED
            # (host-backed): each per-stage jitted fn pulls them onto
            # its own submesh, which a mesh-committed stack can't do.
            max_adapters=getattr(args, 'max_adapters', 8),
            max_rank=getattr(args, 'max_lora_rank', 0),
            mesh=None if num_stages > 1 else mesh)
        inv = adapters.inventory()
        print(f'adapter registry: {len(inv)} adapters in '
              f'{adapter_dir} (max {adapters.max_adapters} '
              f'device-resident): {inv}', flush=True)

    engine_total = (spec_total if args.speculative > 0
                    else args.max_total_len)
    engine = None
    prefill_chunk = getattr(args, 'prefill_chunk', 0)
    prefill_budget = getattr(args, 'prefill_budget', 0)
    pipeline_decode = (False if getattr(args, 'no_pipeline_decode',
                                        False) else None)
    request_timeout = getattr(args, 'request_timeout', 600.0)
    max_queue_requests = getattr(args, 'max_queue_requests', 0)
    max_queue_tokens = getattr(args, 'max_queue_tokens', 0)
    # Disaggregation + tiered-cache knobs. Both need the paged slot
    # engine: the spill tier stores prefix-cache pages, and a prefill
    # role without an exportable prefix cache has nothing to hand off.
    role = getattr(args, 'role', '') or ''
    kv_spill_bytes = int(getattr(args, 'kv_spill_bytes', 0) or 0)
    kv_cold_dir = getattr(args, 'kv_cold_dir', None)
    decode_peers = [p for p in
                    (getattr(args, 'decode_peers', None) or ''
                     ).split(',') if p]
    if (kv_spill_bytes or kv_cold_dir) and \
            not args.continuous_batching:
        raise SystemExit(
            '--kv-spill-bytes/--kv-cold-dir need '
            '--continuous-batching (the spill tier stores evicted '
            'prefix-cache pages of the paged slot engine)')
    if role == 'prefill' and not args.continuous_batching:
        raise SystemExit(
            '--role prefill needs --continuous-batching (the handoff '
            'exports KV page chains from the slot engine\'s prefix '
            'cache)')
    if args.continuous_batching:
        from skypilot_tpu.models.batching import ContinuousBatchingEngine
        decode_chunk = getattr(args, 'decode_chunk', 1)
        if decode_chunk > 1:
            # The chunk writes past a finishing request; clamp like
            # the speculative engine does (fail fast at startup) and
            # ADVERTISE the clamped capacity (limit_for must match
            # what engine.submit accepts).
            clamped = min(engine_total,
                          model.config.max_seq_len - decode_chunk)
            if clamped < engine_total:
                print(f'decode chunking: clamping max_total_len '
                      f'{engine_total} -> {clamped} (chunk writes '
                      f'need N={decode_chunk} tokens of headroom '
                      f'below max_seq_len='
                      f'{model.config.max_seq_len})', flush=True)
            engine_total = clamped
        engine = ContinuousBatchingEngine(
            model, params, num_slots=args.num_slots,
            max_total_len=engine_total,
            prefix_caching=not args.no_prefix_caching,
            speculative_k=args.speculative,
            decode_chunk=decode_chunk,
            prefill_chunk=prefill_chunk,
            prefill_budget=prefill_budget,
            # Auto (None) keeps pipelining off for spec/decode-chunk
            # engines; --no-pipeline-decode forces it off everywhere.
            pipeline_decode=pipeline_decode,
            max_queue_requests=max_queue_requests,
            max_queue_tokens=max_queue_tokens,
            adapter_store=adapters,
            kv_spill_bytes=kv_spill_bytes,
            kv_cold_dir=kv_cold_dir,
            mesh=mesh)

    rt = InferenceRuntime(
        model=model, params=params, vocab_size=vocab_size,
        model_name=(f'hf:{os.path.basename(args.hf)}'
                    if args.hf else args.model),
        max_total_len=args.max_total_len, spec_total=spec_total,
        speculative=args.speculative, engine=engine,
        engine_total=engine_total if engine is not None else None,
        tokenizer_dir=tokenizer_dir,
        prefill_chunk=prefill_chunk, prefill_budget=prefill_budget,
        pipeline_decode=pipeline_decode,
        request_timeout=request_timeout,
        max_queue_requests=max_queue_requests,
        max_queue_tokens=max_queue_tokens,
        adapters=adapters,
        kv_dtype=kv_dtype, weight_dtype=weight_dtype,
        role=role, decode_peers=decode_peers, mesh=mesh)
    from skypilot_tpu.observability import catalog as _obs_catalog
    _obs_catalog.gauge('skypilot_serving_weight_bytes').set(
        rt.weight_bytes)
    _obs_catalog.gauge('skypilot_serving_storage_info').labels(
        kv_dtype=kv_dtype, weight_dtype=weight_dtype).set(1)
    # Distributed tracing: head-sample at the configured rate; the
    # process tag makes this node's spans a distinct pid row in the
    # merged Chrome trace.
    trace_sample = float(getattr(args, 'trace_sample', 0.0) or 0.0)
    if trace_sample > 0.0:
        from skypilot_tpu.observability import tracing
        tracing.configure(sample=trace_sample,
                          seed=getattr(args, 'trace_seed', None),
                          process=role or 'replica')
    # Declarative SLO targets: one tracker feeds both the /stats slo
    # section and the skypilot_serving_slo_* gauges, recorded through
    # the ServingMetrics hooks.
    slo_spec = getattr(args, 'slo', None)
    if slo_spec:
        from skypilot_tpu.observability import slo as slo_lib
        rt.slo_tracker = slo_lib.SloTracker(
            slo_lib.parse_slo(slo_spec))
        rt.metrics.slo = rt.slo_tracker
    return rt
