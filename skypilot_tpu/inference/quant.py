"""Quantized weight storage for serving: per-channel int8 projections.

Decode throughput on TPU is bounded by HBM bytes streamed per token —
weights first, KV pages second. This module halves the weight half:
the big projection matrices (wq/wk/wv/wo, w_gate/w_up/w_down) are
stored as int8 with one f32 scale per OUTPUT channel (symmetric
absmax over the input dim), and dequantized on read INSIDE the jitted
serving functions, so every matmul still runs in bf16/f32 off
on-chip dequantized operands. Embeddings, the LM head, norms, and
biases stay in their serving dtype — they are either accuracy-
critical (norms) or shared with sampling-path numerics (head).

Two pieces:

  - `quantize_params` rewrites the param pytree: a targeted module's
    {'kernel': W} becomes {'kernel_q': int8, 'kernel_scale': f32[out]}
    (bias untouched). Host-side numpy — runs once at server startup.
  - `QuantizedModel` wraps the flax module transparently: `apply`
    dequantizes a quantized `params` tree at trace time (one
    `int8 -> f32 * scale` op per projection, fused by XLA into the
    consumer matmul) and delegates everything else. Every serving
    call site — the continuous engine's jitted fns, the one-shot
    generate buckets, the /v1/completions scorer — works unchanged,
    and LoRA deltas apply in f32 ON TOP of the dequantized base
    (models/lora.py operates on projection outputs, not kernels).

Tensor parallelism composes: `shard_quantized_for_serving` places
kernel_q with the base kernel's NamedSharding and each scale vector
with its output-channel axis (the kernel's axis-1 mesh axis), per the
parallel/serving.py rules.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

#: Projection modules quantized by default: the Llama-family big
#: matmuls (the GQA attention block + SwiGLU MLP). Matches
#: models/lora.py ALL_TARGETS — LoRA and weight quantization cover
#: the same surfaces.
WEIGHT_TARGETS: Tuple[str, ...] = ('wq', 'wk', 'wv', 'wo',
                                   'w_gate', 'w_up', 'w_down')
QUANT_KEY = 'kernel_q'
SCALE_KEY = 'kernel_scale'


def quantize_params(params: Dict[str, Any],
                    targets: Tuple[str, ...] = WEIGHT_TARGETS
                    ) -> Dict[str, Any]:
    """Per-output-channel symmetric int8 quantization of the targeted
    projection kernels; every other leaf passes through untouched
    (as host numpy). scale[j] = max|W[:, j]| / 127; W ~= q * scale."""
    import jax

    def walk(node, name):
        if isinstance(node, dict):
            kernel = node.get('kernel') if name in targets else None
            if kernel is not None and getattr(kernel, 'ndim', 0) == 2:
                w = np.asarray(jax.device_get(kernel), np.float32)
                amax = np.abs(w).max(axis=0)
                scale = (amax / 127.0).astype(np.float32)
                safe = np.where(scale > 0, scale, 1.0)
                q = np.clip(np.rint(w / safe), -127,
                            127).astype(np.int8)
                out = {QUANT_KEY: q, SCALE_KEY: scale}
                for key, val in node.items():
                    if key != 'kernel':
                        out[key] = np.asarray(jax.device_get(val))
                return out
            return {key: walk(val, key) for key, val in node.items()}
        return node

    return walk(params, '')


def is_quantized(params: Any) -> bool:
    """True when the tree holds at least one quantized kernel."""
    if isinstance(params, dict):
        if QUANT_KEY in params:
            return True
        return any(is_quantized(v) for v in params.values())
    return False


def dequantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild a dense param tree in-graph: quantized kernels become
    f32 `int8 * scale` products (the consumer Dense casts to its
    compute dtype). Called at trace time inside every jitted serving
    fn via QuantizedModel.apply — the int8 tensors are what streams
    from HBM; the dequant fuses into the matmul."""
    import jax.numpy as jnp

    def walk(node):
        if isinstance(node, dict):
            if QUANT_KEY in node:
                out = {key: val for key, val in node.items()
                       if key not in (QUANT_KEY, SCALE_KEY)}
                out['kernel'] = (node[QUANT_KEY].astype(jnp.float32) *
                                 node[SCALE_KEY])
                return out
            return {key: walk(val) for key, val in node.items()}
        return node

    return walk(params)


def weight_num_bytes(params: Any) -> int:
    """Device bytes of a (possibly quantized) param tree — the
    skypilot_serving_weight_bytes gauge."""
    import jax
    import jax.numpy as jnp
    return int(sum(
        leaf.size * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(params)))


class QuantizedModel:
    """Transparent dequant-on-read wrapper around a flax model.

    `apply` swaps a quantized `params` collection for its in-graph
    dequantized form before delegating; `init`, `config`, and every
    other attribute delegate to the base model, so the continuous
    engine, the one-shot buckets, the scorer, and the adapter
    registry all serve a quantized model without special cases
    (models/lora.py `supports` unwraps via `base_model`)."""

    def __init__(self, model) -> None:
        self.base_model = model

    @property
    def config(self):
        return self.base_model.config

    def apply(self, variables, *args, **kwargs):
        if isinstance(variables, dict) and \
                is_quantized(variables.get('params')):
            variables = dict(variables)
            variables['params'] = dequantize_params(
                variables['params'])
        return self.base_model.apply(variables, *args, **kwargs)

    def init(self, *args, **kwargs):
        return self.base_model.init(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.base_model, name)


def kv_page_bytes(cfg, kv_dtype: str, shard_ways: int = 1,
                  stages: int = 1) -> int:
    """Device bytes ONE physical KV page costs across all layers ON
    ONE CHIP (K + V values, plus scale slots for int8) — the unit the
    --kv-pool-bytes knob divides by, so a byte budget maps to the
    same HBM spend for either storage format.

    `shard_ways` is how many ways the pool's kv-heads axis shards
    over the mesh (parallel/serving.py kv_shard_ways): each chip then
    stores 1/shard_ways of the VALUE bytes but the FULL scale rows
    (per-token scales replicate — every head shard quantizes against
    the same scale), so an N-way pool's per-chip page is cheaper and
    the same per-chip budget buys ~N x the pages.

    `stages` is the pipeline-stage count (PR 19): each stage's chips
    hold pages for only that stage's layers — the WIDEST stage
    (ceil(num_layers / stages), stage_layer_ranges front-loads the
    remainder) bounds the per-chip cost, so an S-stage T-way mesh
    holds ~S·T x the pages at the same per-chip budget."""
    import jax.numpy as jnp
    per_layer = 2 * cfg.num_kv_heads * cfg.kv_page_size * cfg.head_dim
    if cfg.num_kv_heads % shard_ways:
        raise ValueError(
            f'shard_ways={shard_ways} does not divide num_kv_heads='
            f'{cfg.num_kv_heads} (the GQA remainder rule replicates '
            f'instead — pass shard_ways=1)')
    if stages < 1 or stages > cfg.num_layers:
        raise ValueError(
            f'stages={stages} must be in [1, num_layers='
            f'{cfg.num_layers}]')
    if kv_dtype == 'int8':
        value_bytes = per_layer // shard_ways
        scale_bytes = 2 * cfg.kv_page_size * 4
    else:
        value_bytes = (per_layer // shard_ways *
                       jnp.dtype(cfg.dtype).itemsize)
        scale_bytes = 0
    stage_layers = -(-cfg.num_layers // stages)  # ceil: widest stage
    return stage_layers * (value_bytes + scale_bytes)


def pool_pages_for_bytes(cfg, kv_dtype: str, pool_bytes: int,
                         shard_ways: int = 1, stages: int = 1) -> int:
    """Physical pages a PER-CHIP byte budget buys under `kv_dtype` —
    how serve_lm --kv-pool-bytes sizes kv_total_pages (int8 fits ~2x
    the pages of bf16 in the same bytes; a pool head-sharded
    `shard_ways` ways fits ~shard_ways more again at the same
    per-chip HBM, and splitting layers over `stages` pipeline stages
    multiplies by ~stages on top — each stage stores only its own
    layers' pages)."""
    pages = pool_bytes // kv_page_bytes(cfg, kv_dtype, shard_ways,
                                        stages)
    if pages < 2:
        raise ValueError(
            f'--kv-pool-bytes {pool_bytes} buys {pages} pages '
            f'({kv_page_bytes(cfg, kv_dtype, shard_ways, stages)} '
            f'bytes/page across layers, kv_dtype={kv_dtype}); need '
            f'>= 2 (page 0 is the trash page)')
    return int(pages)


def shard_quantized_for_serving(model, qparams: Dict[str, Any],
                                mesh, rules=None,
                                dtype: Optional[Any] = None
                                ) -> Dict[str, Any]:
    """Tensor-parallel placement of a quantized param tree: kernel_q
    takes the base kernel's NamedSharding, kernel_scale shards over
    the kernel's OUTPUT-channel mesh axis (scales live with their
    channel), everything else places per the base rules — shard-only
    transfers, like shard_params_for_serving. `dtype` casts
    non-quantized leaves per leaf immediately before placement."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.serving import serving_param_shardings
    if rules is None:
        rules = mesh_lib.DEFAULT_RULES
    base = getattr(model, 'base_model', model)
    shardings = serving_param_shardings(base, mesh, rules)

    def place(leaf, sharding, cast):
        if cast and dtype is not None:
            leaf = np.asarray(leaf).astype(dtype)
        return jax.device_put(leaf, sharding)

    def walk(qnode, snode):
        if isinstance(qnode, dict) and QUANT_KEY in qnode:
            kernel_sh = snode['kernel']
            spec = tuple(kernel_sh.spec)
            out_axis = spec[1] if len(spec) > 1 else None
            scale_sh = NamedSharding(mesh, PartitionSpec(out_axis))
            out = {QUANT_KEY: place(qnode[QUANT_KEY], kernel_sh,
                                    cast=False),
                   SCALE_KEY: place(qnode[SCALE_KEY], scale_sh,
                                    cast=False)}
            for key, val in qnode.items():
                if key in (QUANT_KEY, SCALE_KEY):
                    continue
                out[key] = place(val, snode[key], cast=True)
            return out
        if isinstance(qnode, dict):
            return {key: walk(val, snode[key])
                    for key, val in qnode.items()}
        return place(qnode, snode, cast=True)

    return walk(qparams, shardings)
