"""Generate docs/cli.md from the click command tree.

Usage: python docs/gen_cli_md.py > docs/cli.md
"""
import click

from skypilot_tpu.client import cli as cli_mod


def walk(cmd, path):
    ctx = click.Context(cmd, info_name=path)
    if isinstance(cmd, click.Group):
        if path != 'stpu':
            print(f'## `{path}`')
            print()
            if cmd.help:
                print(cmd.help.strip())
                print()
        for name in sorted(cmd.commands):
            walk(cmd.commands[name], f'{path} {name}')
    else:
        print(f'### `{path}`')
        print()
        print('```')
        print(cmd.get_help(ctx))
        print('```')
        print()


def main():
    print('# `stpu` CLI reference')
    print()
    print('Auto-generated from the click command tree '
          '(`python docs/gen_cli_md.py > docs/cli.md`). '
          'Reference analog: `sky --help` (sky/client/cli/command.py).')
    print()
    print('## Top-level commands')
    print()
    group = cli_mod.cli
    for name in sorted(group.commands):
        sub = group.commands[name]
        if not isinstance(sub, click.Group):
            walk(sub, f'stpu {name}')
    for name in sorted(group.commands):
        sub = group.commands[name]
        if isinstance(sub, click.Group):
            walk(sub, f'stpu {name}')


if __name__ == '__main__':
    main()
