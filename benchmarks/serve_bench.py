#!/usr/bin/env python
"""Serving benchmark: req/s + TTFT through the LM inference server.

BASELINE.md north-star #4 ('SkyServe req/s + p50 TTFT'). Drives
recipes/serve_lm.py over HTTP with concurrent closed-loop clients and
reports request throughput and time-to-first-token percentiles, for
both engines:

  python benchmarks/serve_bench.py --engine continuous --requests 64
  python benchmarks/serve_bench.py --engine simple --requests 64

On CPU this exercises the full serving stack with llama-tiny; on a
TPU host pass --model llama3-8b (weights via --ckpt-dir). Prints one
JSON line per run.

TTFT is measured for real over SSE (`stream: true` — the first token
frame's arrival), not a 1-token proxy round-trip. Note: in simple
(one-shot) mode streamed requests ride the lazily-built slot engine —
the product's actual streaming path for that configuration.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import socket
import statistics
import subprocess
import sys
import threading
import time

import requests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--engine', choices=['continuous', 'simple'],
                        default='continuous')
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--requests', type=int, default=64)
    parser.add_argument('--concurrency', type=int, default=8)
    parser.add_argument('--max-total-len', type=int, default=64)
    parser.add_argument('--max-new-tokens', type=int, default=24)
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--speculative', type=int, default=0,
                        metavar='K', help='prompt-lookup speculation '
                        '(works with both engines)')
    parser.add_argument('--decode-chunk', type=int, default=1,
                        metavar='N',
                        help='continuous engine: N decode steps per '
                             'dispatch (dispatch-overhead '
                             'amortization)')
    parser.add_argument('--long-prompt-frac', type=float, default=0.0,
                        metavar='F',
                        help='fraction of requests carrying a LONG '
                             'prompt (near max-total-len minus the '
                             'generation budget) mixed into the short '
                             'workload — the regime where whole-'
                             'prompt prefill stalls inter-token '
                             'latency and chunked prefill should not')
    parser.add_argument('--prefill-chunk', type=int, default=None,
                        metavar='C',
                        help='forwarded to serve_lm --prefill-chunk '
                             '(0 disables chunked prefill for A/B '
                             'runs; default: server default)')
    parser.add_argument('--prefill-budget', type=int, default=None,
                        metavar='T',
                        help='forwarded to serve_lm --prefill-budget')
    parser.add_argument('--no-pipeline-decode', action='store_true',
                        help='forwarded to serve_lm (disables '
                             'host/device decode pipelining)')
    parser.add_argument('--fault-plan', default=None, metavar='JSON',
                        help='forwarded to serve_lm --fault-plan '
                             '(inline JSON or a file path): run the '
                             'workload under injected faults and A/B '
                             'the JSON line against a clean run')
    parser.add_argument('--request-timeout', type=float, default=None,
                        help='forwarded to serve_lm '
                             '--request-timeout')
    parser.add_argument('--max-queue-requests', type=int, default=None,
                        help='forwarded to serve_lm '
                             '--max-queue-requests (shed + 429 when '
                             'saturated; shed count lands in the '
                             'JSON line)')
    parser.add_argument('--max-queue-tokens', type=int, default=None,
                        help='forwarded to serve_lm '
                             '--max-queue-tokens')
    parser.add_argument('--repetitive', action='store_true',
                        help='structured (repeated-trigram) prompts — '
                             'the regime speculation accelerates')
    parser.add_argument('--shared-prefix', type=int, default=0,
                        metavar='N',
                        help='prepend one shared N-token system '
                             'prompt to every request — the regime '
                             'prefix caching accelerates (chatbots, '
                             'few-shot templates)')
    parser.add_argument('--no-prefix-caching', action='store_true')
    parser.add_argument('--hf', default=None,
                        help='serve a local HF checkpoint directory')
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--cpu', action='store_true',
                        help='pin the server to the CPU backend')
    args = parser.parse_args()
    if args.decode_chunk > 1 and args.engine != 'continuous':
        parser.error('--decode-chunk is a continuous-engine knob; '
                     'the one-shot engine would silently ignore it '
                     '(and the A/B record would lie)')

    port = _free_port()
    cmd = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
           '--model', args.model, '--port', str(port),
           '--max-total-len', str(args.max_total_len)]
    if args.engine == 'continuous':
        cmd += ['--continuous-batching', '--num-slots',
                str(args.num_slots)]
    if args.no_prefix_caching:
        cmd += ['--no-prefix-caching']
    if args.speculative:
        cmd += ['--speculative', str(args.speculative)]
    if args.decode_chunk > 1:
        cmd += ['--decode-chunk', str(args.decode_chunk)]
    if args.prefill_chunk is not None:
        cmd += ['--prefill-chunk', str(args.prefill_chunk)]
    if args.prefill_budget is not None:
        cmd += ['--prefill-budget', str(args.prefill_budget)]
    if args.no_pipeline_decode:
        cmd += ['--no-pipeline-decode']
    if args.fault_plan:
        cmd += ['--fault-plan', args.fault_plan]
    if args.request_timeout is not None:
        cmd += ['--request-timeout', str(args.request_timeout)]
    if args.max_queue_requests is not None:
        cmd += ['--max-queue-requests', str(args.max_queue_requests)]
    if args.max_queue_tokens is not None:
        cmd += ['--max-queue-tokens', str(args.max_queue_tokens)]
    if args.hf:
        cmd += ['--hf', args.hf]
    if args.ckpt_dir:
        cmd += ['--ckpt-dir', args.ckpt_dir]
    if args.cpu:
        cmd += ['--cpu']
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    server = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                              stderr=subprocess.STDOUT)
    url = f'http://127.0.0.1:{port}'
    try:
        deadline = time.time() + 300
        info = None
        while time.time() < deadline:
            try:
                info = requests.get(url, timeout=2).json()
                break
            except requests.RequestException:
                time.sleep(1)
                if server.poll() is not None:
                    raise RuntimeError('serve_lm died')
        if info is None:
            raise RuntimeError('serve_lm not ready within 300s')
        vocab = int(info['vocab_size'])

        rng = random.Random(0)
        if args.repetitive:
            # Structured prompts (repeated trigrams): the shape
            # prompt-lookup speculation exploits — code, templated
            # text, retrieval contexts.
            def rep_prompt():
                gram = [rng.randrange(1, vocab) for _ in range(3)]
                n = rng.randrange(4, 16)
                return (gram * ((n + 2) // 3))[:n]
            prompts = [rep_prompt() for _ in range(args.requests)]
        else:
            prompts = [[rng.randrange(1, vocab)
                        for _ in range(rng.randrange(4, 16))]
                       for _ in range(args.requests)]
        if args.long_prompt_frac > 0:
            # Long prompts leave room to generate the full
            # max_new_tokens below max_total_len (submit requires
            # prompt_len < max_total_len).
            long_len = max(16, args.max_total_len -
                           args.max_new_tokens - 2)
            n_long = int(round(args.long_prompt_frac * len(prompts)))
            # Deterministic spread through the workload (not a
            # front-loaded burst).
            for i in range(n_long):
                idx = (i * len(prompts)) // max(n_long, 1)
                prompts[idx] = [rng.randrange(1, vocab)
                                for _ in range(long_len)]
        if args.shared_prefix:
            system = [rng.randrange(1, vocab)
                      for _ in range(args.shared_prefix)]
            prompts = [system + p for p in prompts]
        # Warm the compile caches (prefill buckets + decode). With
        # prefix caching the SECOND pass over a prompt takes the
        # suffix-prefill path (different bucket shapes) — warm the
        # shortest and longest so the timed section measures serving,
        # not XLA compiles.
        warm = [prompts[0]]
        if args.shared_prefix or args.long_prompt_frac > 0:
            warm.append(min(prompts, key=len))
            warm.append(max(prompts, key=len))
        for p in warm:
            for _ in range(2):
                requests.post(f'{url}/generate', json={
                    'tokens': [p], 'max_new_tokens': 2}, timeout=600)
        # Streaming warm-up: in simple mode the first streamed request
        # builds the lazy stream engine + its compiles (the timed
        # section must measure serving, not XLA).
        requests.post(f'{url}/generate', json={
            'tokens': [prompts[0]], 'max_new_tokens': 2,
            'stream': True}, timeout=600)

        latencies = []
        itl_gaps = []    # inter-token gaps across ALL requests (s)
        shed = [0]       # client-observed 429s (admission control)
        lock = threading.Lock()
        queue = list(enumerate(prompts))

        def client() -> None:
            while True:
                with lock:
                    if not queue:
                        return
                    _idx, prompt = queue.pop()
                t0 = time.perf_counter()
                # REAL TTFT + ITL: stream the request (SSE), stamp the
                # first token frame and every gap between consecutive
                # token frames — one request measures TTFT, ITL, and
                # completion latency, exactly what a streaming client
                # experiences.
                ttft = None
                last_tok_t = None
                gaps = []
                with requests.post(f'{url}/generate', json={
                        'tokens': [prompt],
                        'max_new_tokens': args.max_new_tokens,
                        'stream': True}, timeout=600,
                        stream=True) as resp:
                    if resp.status_code == 429:
                        # Shed by admission control: count it and move
                        # on (a production client would honor
                        # Retry-After; the bench measures degradation,
                        # not retries).
                        with lock:
                            shed[0] += 1
                        continue
                    resp.raise_for_status()
                    for raw in resp.iter_lines():
                        if not raw.startswith(b'data: '):
                            continue
                        if b'"token"' in raw:
                            now = time.perf_counter()
                            if ttft is None:
                                ttft = now - t0
                            if last_tok_t is not None:
                                gaps.append(now - last_tok_t)
                            last_tok_t = now
                        if raw == b'data: [DONE]':
                            break
                total = time.perf_counter() - t0
                with lock:
                    latencies.append((ttft if ttft is not None
                                      else total, total))
                    itl_gaps.extend(gaps)

        start = time.perf_counter()
        threads = [threading.Thread(target=client)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start

        ttfts = sorted(l[0] for l in latencies)
        gaps = sorted(itl_gaps)
        # Server-side ITL percentiles (/stats): gaps measured at the
        # engine's token COMMIT, the signal chunked prefill targets —
        # client-side SSE gap timing rides TCP flush batching and
        # client GIL scheduling, which can swamp ms-scale effects.
        stats = requests.get(f'{url}/stats', timeout=30).json()
        serving = stats['serving']

        def pct(sorted_vals, q):
            if not sorted_vals:
                return None
            return round(1000 * sorted_vals[
                int(q * (len(sorted_vals) - 1))], 2)

        print(json.dumps({
            'engine': args.engine,
            'speculative': args.speculative,
            'decode_chunk': args.decode_chunk,
            'prefill_chunk': args.prefill_chunk,
            'prefill_budget': args.prefill_budget,
            'pipeline_decode': not args.no_pipeline_decode,
            'shared_prefix': args.shared_prefix,
            'long_prompt_frac': args.long_prompt_frac,
            'prefix_caching': not args.no_prefix_caching,
            'model': info['model'],   # server-reported (handles --hf)
            'requests': len(latencies),
            'concurrency': args.concurrency,
            'req_per_sec': round(len(latencies) / elapsed, 2),
            'p50_ttft_ms': (round(1000 * statistics.median(ttfts), 1)
                            if ttfts else None),
            'p95_ttft_ms': (round(
                1000 * ttfts[int(0.95 * (len(ttfts) - 1))], 1)
                if ttfts else None),
            'p99_ttft_ms': pct(ttfts, 0.99),
            'itl_ms_p50': serving.get('itl_ms_p50'),
            'itl_ms_p99': serving.get('itl_ms_p99'),
            'sse_itl_ms_p50': pct(gaps, 0.50),
            'sse_itl_ms_p99': pct(gaps, 0.99),
            # Robustness plane: degradation under --fault-plan /
            # admission control is A/B-able from the same JSON line.
            'fault_plan': bool(args.fault_plan),
            'shed_requests': shed[0],
            'server_requests_shed': serving.get('requests_shed'),
            'server_deadline_exceeded':
                serving.get('deadline_exceeded'),
            'engine_restarts': stats.get('engine_restarts'),
        }))
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


if __name__ == '__main__':
    main()
