#!/usr/bin/env python
"""Serving benchmark: req/s + TTFT through the LM inference server.

BASELINE.md north-star #4 ('SkyServe req/s + p50 TTFT'). Drives
recipes/serve_lm.py over HTTP with concurrent closed-loop clients and
reports request throughput and time-to-first-token percentiles, for
both engines:

  python benchmarks/serve_bench.py --engine continuous --requests 64
  python benchmarks/serve_bench.py --engine simple --requests 64

On CPU this exercises the full serving stack with llama-tiny; on a
TPU host pass --model llama3-8b (weights via --ckpt-dir). Prints one
JSON line per run.

TTFT is measured for real over SSE (`stream: true` — the first token
frame's arrival), not a 1-token proxy round-trip. Note: in simple
(one-shot) mode streamed requests ride the lazily-built slot engine —
the product's actual streaming path for that configuration.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import threading
import time

import requests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # fleet mode imports skypilot_tpu in-process


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def pct_ms(sorted_vals, q):
    """Linear-interpolated percentile of sorted SECONDS, in ms.
    Nearest-rank at bench-sized N collapsed distinct percentiles
    onto one sample (BENCH_lora_r10's p95_ttft 1480.4 vs p99 1482.62
    were the same observation); interpolation keeps them honest —
    always read them next to the block's n_samples."""
    if not sorted_vals:
        return None
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return round(1000.0 * (sorted_vals[lo] * (1.0 - frac) +
                           sorted_vals[hi] * frac), 2)


def _slo_observed(record: dict) -> dict:
    """Map a bench record's measured keys onto SLO dimensions.
    Engine-side ITL (decode_itl_ms_p99 / server itl_ms_p99) beats the
    SSE-timing fallback — wire jitter is not a scheduler promise.
    Errors fold in server-reported 504s so single-server and fleet
    records score the same promise."""
    requests = record.get('requests') or 0
    itl = record.get('decode_itl_ms_p99')
    if itl is None:
        itl = record.get('itl_ms_p99')
    if itl is None:
        itl = record.get('sse_itl_ms_p99')
    errors = record.get('client_errors')
    deadline = record.get('server_deadline_exceeded')
    error_rate = None
    if requests and (errors is not None or deadline is not None):
        error_rate = ((errors or 0) + (deadline or 0)) / float(requests)
    shed = record.get('shed_requests')
    shed_rate = None
    if shed is not None and (requests + shed) > 0:
        shed_rate = shed / float(requests + shed)
    return {
        'p99_ttft_ms': record.get('p99_ttft_ms'),
        'p99_itl_ms': itl,
        'error_rate': error_rate,
        'shed_rate': shed_rate,
    }


def attach_slo(record: dict, targets: dict) -> dict:
    """Score a bench record (or each entry of an A/B `runs` map)
    against `targets` and attach the machine-checkable `slo` block —
    only the targeted dimensions are scored; an unmeasured targeted
    dimension fails (slo.evaluate's contract)."""
    from skypilot_tpu.observability import slo as slo_lib
    if not isinstance(record, dict):
        return record
    runs = record.get('runs')
    if isinstance(runs, dict):
        for run in runs.values():
            attach_slo(run, targets)
        record['slo'] = {
            'ok': all(bool((r or {}).get('slo', {}).get('ok'))
                      for r in runs.values()),
            'runs': {name: (r or {}).get('slo', {}).get('ok')
                     for name, r in runs.items()},
        }
        return record
    observed = {dim: val for dim, val in _slo_observed(record).items()
                if dim in targets}
    record['slo'] = slo_lib.evaluate(targets, observed)
    return record


def _server_env(args) -> dict:
    """Environment for a spawned serve_lm: repo on PYTHONPATH, and —
    for --tensor N on CPU — N virtual host devices (the ROADMAP
    multi-device-without-TPUs harness)."""
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    if getattr(args, 'paged_impl', None):
        # The paged-attention impl is resolved at trace time from
        # this env var (ops/pallas_paged.resolve_impl) — serve_lm
        # needs no flag of its own.
        env['SKYPILOT_TPU_PAGED_IMPL'] = args.paged_impl
    chips = max(args.tensor, 1) * max(getattr(args, 'stages', 1), 1)
    if chips > 1:
        flags = env.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' not in flags:
            env['XLA_FLAGS'] = (
                f'{flags} --xla_force_host_platform_device_count='
                f'{chips}').strip()
    return env


def _build_server_cmd(args, adapter_dir=None) -> list:
    """serve_lm command line WITHOUT --port (single-server mode
    appends one; fleet mode lets the replica manager assign them)."""
    cmd = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
           '--model', args.model,
           '--max-total-len', str(args.max_total_len)]
    if args.kv_dtype:
        cmd += ['--kv-dtype', args.kv_dtype]
    if args.kv_pool_bytes:
        cmd += ['--kv-pool-bytes', str(args.kv_pool_bytes)]
    if args.weight_dtype:
        cmd += ['--weight-dtype', args.weight_dtype]
    if args.kv_spill_bytes:
        cmd += ['--kv-spill-bytes', str(args.kv_spill_bytes)]
    if args.kv_cold_dir:
        cmd += ['--kv-cold-dir', args.kv_cold_dir]
    if args.tensor > 1:
        cmd += ['--tensor', str(args.tensor)]
    if getattr(args, 'stages', 1) > 1:
        cmd += ['--stages', str(args.stages)]
    if adapter_dir:
        cmd += ['--adapter-dir', adapter_dir,
                '--max-adapters', str(max(args.max_adapters,
                                          args.adapters))]
    if args.engine == 'continuous':
        cmd += ['--continuous-batching', '--num-slots',
                str(args.num_slots)]
    if args.no_prefix_caching:
        cmd += ['--no-prefix-caching']
    if args.speculative:
        cmd += ['--speculative', str(args.speculative)]
    if args.decode_chunk > 1:
        cmd += ['--decode-chunk', str(args.decode_chunk)]
    if args.prefill_chunk is not None:
        cmd += ['--prefill-chunk', str(args.prefill_chunk)]
    if args.prefill_budget is not None:
        cmd += ['--prefill-budget', str(args.prefill_budget)]
    if args.no_pipeline_decode:
        cmd += ['--no-pipeline-decode']
    if args.fault_plan:
        cmd += ['--fault-plan', args.fault_plan]
    if args.request_timeout is not None:
        cmd += ['--request-timeout', str(args.request_timeout)]
    if args.max_queue_requests is not None:
        cmd += ['--max-queue-requests', str(args.max_queue_requests)]
    if args.max_queue_tokens is not None:
        cmd += ['--max-queue-tokens', str(args.max_queue_tokens)]
    if args.hf:
        cmd += ['--hf', args.hf]
    if args.ckpt_dir:
        cmd += ['--ckpt-dir', args.ckpt_dir]
    if args.cpu:
        cmd += ['--cpu']
    return cmd


def _make_adapter_artifacts(args, out_dir: str) -> list:
    """Generate --adapters N random adapter artifacts for the bench
    model (deterministic: adapter i is seeded with i). Imports the
    model registry in-process only for the config geometry."""
    from skypilot_tpu.models import lora as lora_lib
    from skypilot_tpu.recipes.train_lm import _build_model
    model, _, _ = _build_model(args.model, args.max_total_len,
                               remat=False)
    spec = lora_lib.LoraSpec(rank=args.adapter_rank,
                             alpha=2.0 * args.adapter_rank)
    names = []
    for i in range(args.adapters):
        name = f'ad{i:03d}'
        params = lora_lib.random_adapter_params(i, model.config, spec)
        lora_lib.save_adapter(os.path.join(out_dir, name), params,
                              spec, base_model=args.model)
        names.append(name)
    return names


def _adapter_assignment(args, names: list) -> list:
    """Deterministic per-request adapter assignment. `uniform` draws
    each adapter equally; `zipf` draws adapter k with weight
    1/(k+1) — the few-hot-tenants regime that exercises the LRU
    (cold adapters keep evicting and reloading)."""
    rng = random.Random(1)
    if args.adapter_mix == 'uniform':
        return [names[rng.randrange(len(names))]
                for _ in range(args.requests)]
    weights = [1.0 / (k + 1) for k in range(len(names))]
    total = sum(weights)
    cum = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cum.append(acc)
    out = []
    for _ in range(args.requests):
        r = rng.random()
        idx = next(i for i, c in enumerate(cum) if r <= c)
        out.append(names[idx])
    return out


def _fleet_prompts(args, vocab: int, rng) -> list:
    """The fleet workload: random short prompts, each carrying one of
    `--prefix-groups` distinct shared system prefixes (group = request
    index mod groups — deterministic, interleaved). Multiple groups
    are what separates the policies: under affinity each group pins to
    one replica (its pages cached once); under round-robin every
    replica pays and caches every group's pages."""
    prompts = [[rng.randrange(1, vocab)
                for _ in range(rng.randrange(4, 16))]
               for _ in range(args.requests)]
    if args.shared_prefix:
        groups = max(1, args.prefix_groups or 8)
        systems = [[rng.randrange(1, vocab)
                    for _ in range(args.shared_prefix)]
                   for _ in range(groups)]
        # Seeded-random group per request, NOT i % groups: a modulo
        # assignment correlates with round-robin's i % replicas and
        # accidentally pins groups under the control policy.
        prompts = [systems[rng.randrange(groups)] + p
                   for p in prompts]
    if args.long_prompt_frac > 0:
        # Unique (uncached) long prompts spread through the workload:
        # the compute-bound prefill traffic the disaggregated arm
        # moves off the decode pool.
        long_len = args.long_prompt_len or max(
            16, args.max_total_len - args.max_new_tokens - 2)
        n_long = int(round(args.long_prompt_frac * len(prompts)))
        for i in range(n_long):
            idx = (i * len(prompts)) // max(n_long, 1)
            prompts[idx] = [rng.randrange(1, vocab)
                            for _ in range(long_len)]
    return prompts


def _run_fleet_once(args, policy_name: str) -> dict:
    """One fleet run under one LB policy: spawn --replicas servers
    behind the replica-plane LB, drive the workload through it,
    report per-replica breakdown + affinity ratio."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import \
        load_balancing_policies  # noqa: F401 (registers policies)
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane import replica_manager as rm
    from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY

    env = _server_env(args)
    if args.stub_replicas:
        factory = rm.stub_factory(
            extra_args=['--cache-pages', str(args.stub_cache_pages),
                        '--token-sleep-ms',
                        str(args.stub_token_sleep_ms),
                        '--prefill-ms-per-token',
                        str(args.stub_prefill_ms_per_token)],
            env=env)
    else:
        factory = rm.serve_lm_factory(_build_server_cmd(args),
                                      env=env)
    spec = spec_lib.SkyServiceSpec(min_replicas=args.replicas,
                                   max_replicas=args.replicas)
    autoscaler = autoscalers.EngineMetricsAutoscaler(spec)
    policy = LB_POLICY_REGISTRY.from_str(policy_name)()
    # Disaggregated arm: a prefill pool of --prefill-replicas behind
    # the LB's prompt-length threshold, handing KV chains to the
    # decode pool.
    disagg = args.prefill_replicas > 0
    prefill_autoscaler = None
    prefill_pool = None
    if disagg:
        from skypilot_tpu.serve.replica_plane import PrefillPool
        pspec = spec_lib.SkyServiceSpec(
            min_replicas=args.prefill_replicas,
            max_replicas=args.prefill_replicas)
        prefill_autoscaler = autoscalers.EngineMetricsAutoscaler(
            pspec)
        prefill_pool = PrefillPool()
    # --state-dir journals the bench fleet too (the per-policy
    # subdir keeps the A/B arms' journals separate): benches double
    # as adoption drills — SIGKILL the bench and the replicas can be
    # adopted or reaped by a serve_fleet pointed at the same dir.
    state_dir = (os.path.join(args.state_dir, policy_name)
                 if args.state_dir else None)
    # Generous scrape tolerance: on a saturated 1-core bench host a
    # slow /stats answer is load, not death — flapping NOT_READY
    # would make the fixed-size autoscaler spawn replacement
    # interpreters mid-run, which worsens the very contention that
    # slowed the scrape (a spawn spiral the 30s-timeout fleet
    # defaults are not tuned against).
    manager = ReplicaManager(factory, drain_grace_s=30.0,
                             scrape_timeout_s=20.0,
                             max_scrape_failures=1000,
                             state_dir=state_dir)
    controller = FleetController(
        manager, policy, autoscaler, interval_s=1.0,
        prefill_autoscaler=prefill_autoscaler,
        prefill_pool=prefill_pool)
    lb_port = _free_port()
    lb = make_lb_server(
        policy, lb_port, policy_name=policy_name, manager=manager,
        disagg_threshold=(args.disagg_prompt_threshold
                          if disagg else 0),
        prefill_pool=prefill_pool)
    lb_thread = threading.Thread(target=lb.serve_forever, daemon=True)
    lb_thread.start()
    url = f'http://127.0.0.1:{lb_port}'
    try:
        for _ in range(args.replicas):
            manager.spawn(role='decode' if disagg else '')
        for _ in range(args.prefill_replicas):
            manager.spawn(role='prefill')
        total = args.replicas + args.prefill_replicas
        if not controller.wait_ready(total, timeout_s=300):
            raise RuntimeError(
                f'fleet of {total} not ready within 300s')
        controller.tick()  # push roles/peers before traffic
        info = requests.get(url, timeout=10).json()  # via LB
        vocab = int(info['vocab_size'])

        rng = random.Random(0)
        prompts = _fleet_prompts(args, vocab, rng)
        if not args.stub_replicas:
            # Warm every replica's compile caches directly (through
            # the LB, affinity would warm only each prompt's target).
            warm = [min(prompts, key=len), max(prompts, key=len)]
            for view in manager.views():
                for p in warm:
                    for _ in range(2):
                        requests.post(
                            f'http://{view.endpoint}/generate',
                            json={'tokens': [p],
                                  'max_new_tokens': 2}, timeout=600)

        ticker = threading.Thread(target=controller.run, daemon=True)
        ticker.start()

        latencies = []
        itl_gaps = []    # SSE inter-token gaps across ALL requests
        errors = [0]
        shed = [0]
        lock = threading.Lock()
        queue = list(enumerate(prompts))

        def client() -> None:
            while True:
                with lock:
                    if not queue:
                        return
                    _idx, prompt = queue.pop()
                t0 = time.perf_counter()
                ttft = None
                last_tok_t = None
                gaps = []
                try:
                    with requests.post(f'{url}/generate', json={
                            'tokens': [prompt],
                            'max_new_tokens': args.max_new_tokens,
                            'stream': True}, timeout=600,
                            stream=True) as resp:
                        if resp.status_code == 429:
                            with lock:
                                shed[0] += 1
                            continue
                        if resp.status_code >= 500:
                            with lock:
                                errors[0] += 1
                            continue
                        for raw in resp.iter_lines():
                            if not raw.startswith(b'data: '):
                                continue
                            if b'"token"' in raw:
                                now = time.perf_counter()
                                if ttft is None:
                                    ttft = now - t0
                                if last_tok_t is not None:
                                    gaps.append(now - last_tok_t)
                                last_tok_t = now
                            if raw == b'data: [DONE]':
                                break
                except requests.RequestException:
                    with lock:
                        errors[0] += 1
                    continue
                total = time.perf_counter() - t0
                with lock:
                    latencies.append((ttft if ttft is not None
                                      else total, total))
                    itl_gaps.extend(gaps)

        start = time.perf_counter()
        threads = [threading.Thread(target=client)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start

        manager.scrape_once()  # final per-replica stats
        snap = lb.lb_metrics.snapshot()
        views = sorted(manager.views(), key=lambda v: v.replica_id)
        total_hits = sum(v.prefix_hits for v in views)
        total_misses = sum(v.prefix_misses for v in views)
        ttfts = sorted(l[0] for l in latencies)
        gaps_sorted = sorted(itl_gaps)
        handoffs = {'handoffs': 0, 'failures': 0, 'kv_imports': 0}
        # DECODE-pool engine-side ITL: token-commit gaps scraped from
        # the replicas themselves (stub /stats ships the raw recent
        # gaps) — client SSE timing rides TCP buffering and misses
        # ms-scale engine contention. This is the number the disagg
        # sweep's acceptance gate reads.
        engine_gaps = []
        for v in views:
            h = (v.last_stats or {}).get('handoff') or {}
            for k in handoffs:
                handoffs[k] += int(h.get(k, 0) or 0)
            if disagg and v.role == 'prefill':
                continue
            engine_gaps.extend(
                float(g) / 1000.0 for g in
                ((v.last_stats or {}).get('itl_gaps_ms') or []))
        engine_gaps.sort()

        return {
            'lb_policy': policy_name,
            'replicas': args.replicas,
            'prefill_replicas': args.prefill_replicas,
            'disagg_prompt_threshold': (args.disagg_prompt_threshold
                                        if disagg else 0),
            'long_prompt_frac': args.long_prompt_frac,
            'requests': len(latencies),
            'client_errors': errors[0],
            'shed_requests': shed[0],
            'req_per_sec': round(len(latencies) / elapsed, 2),
            'ttft_n_samples': len(ttfts),
            'p50_ttft_ms': pct_ms(ttfts, 0.50),
            'p95_ttft_ms': pct_ms(ttfts, 0.95),
            'p99_ttft_ms': pct_ms(ttfts, 0.99),
            'sse_itl_n_samples': len(gaps_sorted),
            'sse_itl_ms_p50': pct_ms(gaps_sorted, 0.50),
            'sse_itl_ms_p99': pct_ms(gaps_sorted, 0.99),
            'decode_itl_n_samples': len(engine_gaps),
            'decode_itl_ms_p50': pct_ms(engine_gaps, 0.50),
            'decode_itl_ms_p99': pct_ms(engine_gaps, 0.99),
            'affinity_hit_ratio': snap['affinity_hit_ratio'],
            'lb_routed': snap['routed'],
            'lb_retried': snap['retried'],
            'handoffs': handoffs,
            'fleet_prefix_hit_rate': round(
                total_hits / max(total_hits + total_misses, 1), 4),
            'per_replica': [{
                'replica_id': v.replica_id,
                'role': v.role,
                'routed': snap['routed_per_replica'].get(
                    v.endpoint, 0),
                'prefix_hits': v.prefix_hits,
                'prefix_misses': v.prefix_misses,
                'prefix_hit_rate': round(v.prefix_hit_rate, 4),
                'kv_spill_bytes': v.kv_spill_bytes,
                'kv_restored_pages': v.kv_restored_pages,
            } for v in views],
        }
    finally:
        controller.shutdown()
        lb.shutdown()


def run_fleet(args) -> dict:
    """The --replicas N mode: one run per policy (--ab-policies runs
    prefix_affinity AND round_robin over the identical workload — the
    committed BENCH_serve_fleet JSON)."""
    policies = (['prefix_affinity', 'round_robin']
                if args.ab_policies else [args.lb_policy])
    runs = {name: _run_fleet_once(args, name) for name in policies}
    if not args.ab_policies:
        return runs[args.lb_policy]
    return {
        'bench': 'serve_fleet',
        'engine': args.engine,
        'model': args.model,
        'replicas': args.replicas,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'shared_prefix': args.shared_prefix,
        'prefix_groups': args.prefix_groups,
        'stub_replicas': bool(args.stub_replicas),
        'runs': runs,
    }


def _run_single(args, adapter_dir=None, assignment=None) -> dict:
    """One single-server run (the non-fleet mode), returning the JSON
    record. `adapter_dir` arms serve_lm's adapter registry;
    `assignment` (list of adapter names per request index, None
    entries = base) drives the multi-LoRA workload."""
    port = _free_port()
    cmd = _build_server_cmd(args, adapter_dir) + ['--port', str(port)]
    env = _server_env(args)
    server = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                              stderr=subprocess.STDOUT)
    url = f'http://127.0.0.1:{port}'
    try:
        deadline = time.time() + 300
        info = None
        while time.time() < deadline:
            try:
                info = requests.get(url, timeout=2).json()
                break
            except requests.RequestException:
                time.sleep(1)
                if server.poll() is not None:
                    raise RuntimeError('serve_lm died')
        if info is None:
            raise RuntimeError('serve_lm not ready within 300s')
        vocab = int(info['vocab_size'])

        rng = random.Random(0)
        if args.repetitive:
            # Structured prompts (repeated trigrams): the shape
            # prompt-lookup speculation exploits — code, templated
            # text, retrieval contexts.
            def rep_prompt():
                gram = [rng.randrange(1, vocab) for _ in range(3)]
                n = rng.randrange(4, 16)
                return (gram * ((n + 2) // 3))[:n]
            prompts = [rep_prompt() for _ in range(args.requests)]
        else:
            prompts = [[rng.randrange(1, vocab)
                        for _ in range(rng.randrange(4, 16))]
                       for _ in range(args.requests)]
        if args.long_prompt_frac > 0:
            # Long prompts leave room to generate the full
            # max_new_tokens below max_total_len (submit requires
            # prompt_len < max_total_len).
            long_len = max(16, args.max_total_len -
                           args.max_new_tokens - 2)
            n_long = int(round(args.long_prompt_frac * len(prompts)))
            # Deterministic spread through the workload (not a
            # front-loaded burst).
            for i in range(n_long):
                idx = (i * len(prompts)) // max(n_long, 1)
                prompts[idx] = [rng.randrange(1, vocab)
                                for _ in range(long_len)]
        if args.shared_prefix:
            # --prefix-groups G > 1: G distinct shared prefixes with
            # seeded-random assignment (the multi-session residency
            # regime the quant A/B measures — more pool pages keep
            # more groups' pages resident). Default 1 = the classic
            # one-system-prompt workload.
            groups = max(1, args.prefix_groups or 1)
            systems = [[rng.randrange(1, vocab)
                        for _ in range(args.shared_prefix)]
                       for _ in range(groups)]
            prompts = [systems[rng.randrange(groups)] + p
                       for p in prompts]
        # Warm the compile caches (prefill buckets + decode). With
        # prefix caching the SECOND pass over a prompt takes the
        # suffix-prefill path (different bucket shapes) — warm the
        # shortest and longest so the timed section measures serving,
        # not XLA compiles.
        warm = [prompts[0]]
        if args.shared_prefix or args.long_prompt_frac > 0:
            warm.append(min(prompts, key=len))
            warm.append(max(prompts, key=len))
        for p in warm:
            for _ in range(2):
                requests.post(f'{url}/generate', json={
                    'tokens': [p], 'max_new_tokens': 2}, timeout=600)
        # Streaming warm-up: in simple mode the first streamed request
        # builds the lazy stream engine + its compiles (the timed
        # section must measure serving, not XLA).
        requests.post(f'{url}/generate', json={
            'tokens': [prompts[0]], 'max_new_tokens': 2,
            'stream': True}, timeout=600)
        if assignment:
            # LoRA-variant traces compile on the first adapter lane
            # (shared decode + prefill); one warm request covers them.
            requests.post(f'{url}/generate', json={
                'tokens': [prompts[0]], 'max_new_tokens': 2,
                'stream': True, 'model': assignment[0]}, timeout=600)

        # Window baseline for the engine's CUMULATIVE counters
        # (decode_stall_s, prefill_chunks_run, tokens_committed):
        # deltas over the timed section become honest rates — the
        # lifetime values fold warm-up compiles into the quotient.
        try:
            stats0 = requests.get(f'{url}/stats', timeout=30).json()
        except requests.RequestException:
            stats0 = {}

        latencies = []
        itl_gaps = []    # inter-token gaps across ALL requests (s)
        shed = [0]       # client-observed 429s (admission control)
        adapter_counts: dict = {}
        lock = threading.Lock()
        queue = list(enumerate(prompts))

        def client() -> None:
            while True:
                with lock:
                    if not queue:
                        return
                    idx, prompt = queue.pop()
                body = {'tokens': [prompt],
                        'max_new_tokens': args.max_new_tokens,
                        'stream': True}
                if assignment and assignment[idx] is not None:
                    body['model'] = assignment[idx]
                t0 = time.perf_counter()
                # REAL TTFT + ITL: stream the request (SSE), stamp the
                # first token frame and every gap between consecutive
                # token frames — one request measures TTFT, ITL, and
                # completion latency, exactly what a streaming client
                # experiences.
                ttft = None
                last_tok_t = None
                gaps = []
                with requests.post(f'{url}/generate', json=body,
                                   timeout=600, stream=True) as resp:
                    if resp.status_code == 429:
                        # Shed by admission control: count it and move
                        # on (a production client would honor
                        # Retry-After; the bench measures degradation,
                        # not retries).
                        with lock:
                            shed[0] += 1
                        continue
                    resp.raise_for_status()
                    for raw in resp.iter_lines():
                        if not raw.startswith(b'data: '):
                            continue
                        if b'"token"' in raw:
                            now = time.perf_counter()
                            if ttft is None:
                                ttft = now - t0
                            if last_tok_t is not None:
                                gaps.append(now - last_tok_t)
                            last_tok_t = now
                        if raw == b'data: [DONE]':
                            break
                total = time.perf_counter() - t0
                with lock:
                    latencies.append((ttft if ttft is not None
                                      else total, total))
                    itl_gaps.extend(gaps)
                    name = (assignment[idx] if assignment else None) \
                        or '<base>'
                    adapter_counts[name] = \
                        adapter_counts.get(name, 0) + 1

        start = time.perf_counter()
        threads = [threading.Thread(target=client)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start

        ttfts = sorted(l[0] for l in latencies)
        gaps = sorted(itl_gaps)
        # Server-side ITL percentiles (/stats): gaps measured at the
        # engine's token COMMIT, the signal chunked prefill targets —
        # client-side SSE gap timing rides TCP flush batching and
        # client GIL scheduling, which can swamp ms-scale effects.
        stats = requests.get(f'{url}/stats', timeout=30).json()
        serving = stats['serving']

        record = {
            'engine': args.engine,
            'speculative': args.speculative,
            'decode_chunk': args.decode_chunk,
            'prefill_chunk': args.prefill_chunk,
            'prefill_budget': args.prefill_budget,
            'pipeline_decode': not args.no_pipeline_decode,
            'shared_prefix': args.shared_prefix,
            'long_prompt_frac': args.long_prompt_frac,
            'prefix_caching': not args.no_prefix_caching,
            'model': info['model'],   # server-reported (handles --hf)
            'requests': len(latencies),
            'concurrency': args.concurrency,
            # Quantized-serving + tensor-parallel arms: storage
            # formats, the pool geometry the byte budget bought, and
            # req/s normalized per chip (the ROADMAP item-1 scaling
            # scoreboard — on CPU a "chip" is a virtual host device).
            'kv_dtype': (stats.get('storage') or {}).get('kv_dtype',
                                                         'bf16'),
            'weight_dtype': (stats.get('storage') or {}).get(
                'weight_dtype', 'bf16'),
            'weight_bytes': (stats.get('storage') or {}).get(
                'weight_bytes'),
            'kv_pages_total': (stats.get('page_pool') or {}).get(
                'total'),
            'kv_pool_bytes': (stats.get('page_pool') or {}).get(
                'pool_bytes'),
            # Sharded-pool geometry (PR 15): chips in the mesh, how
            # many ways the pool's kv-heads axis shards, and the
            # per-chip resident bytes (--kv-pool-bytes budgets the
            # LATTER — N sharded chips hold ~Nx kv_pages_total).
            'mesh_devices': (stats.get('storage') or {}).get(
                'mesh_devices'),
            'kv_shard_ways': (stats.get('page_pool') or {}).get(
                'shard_ways'),
            'kv_pool_bytes_per_device': (stats.get('page_pool')
                                         or {}).get(
                'pool_bytes_per_device'),
            # Pipeline-parallel serving (PR 19): per-stage pool split
            # (each stage owns only its layer range's bytes) and the
            # engine's closed-form (S-1)/(M+S-1) bubble of the last
            # prefill burst.
            'kv_pool_stages': (stats.get('page_pool') or {}).get(
                'stages'),
            'pipeline_stages': stats.get('pipeline_stages'),
            'prefill_bubble_fraction': stats.get(
                'prefill_bubble_fraction'),
            'prefix_hit_rate': (stats.get('prefix_cache') or {}).get(
                'hit_rate'),
            'prefix_evictions': (stats.get('prefix_cache') or {}).get(
                'evictions'),
            # Page-pressure preemptions: >0 means the pool could NOT
            # sustain the offered concurrency at this byte budget —
            # the "int8 sustains slots bf16 cannot" signal.
            'preemptions': stats.get('preemptions'),
            # Tiered cache: the spill tier's accounting (None when
            # the server runs without --kv-spill-bytes).
            'kv_spill': stats.get('kv_spill'),
            'tensor': args.tensor,
            'stages': max(getattr(args, 'stages', 1), 1),
            'req_per_sec': round(len(latencies) / elapsed, 2),
            # "chips" = the full (stage, tensor) mesh: per-chip
            # numbers stay comparable between TP-only and TPxPP arms
            # at equal device count.
            'per_chip_req_per_sec': round(
                len(latencies) / elapsed /
                (max(args.tensor, 1) *
                 max(getattr(args, 'stages', 1), 1)), 2),
            'ttft_n_samples': len(ttfts),
            'p50_ttft_ms': pct_ms(ttfts, 0.50),
            'p95_ttft_ms': pct_ms(ttfts, 0.95),
            'p99_ttft_ms': pct_ms(ttfts, 0.99),
            'itl_ms_n': serving.get('itl_ms_n'),
            'itl_ms_p50': serving.get('itl_ms_p50'),
            'itl_ms_p99': serving.get('itl_ms_p99'),
            'sse_itl_n_samples': len(gaps),
            'sse_itl_ms_p50': pct_ms(gaps, 0.50),
            'sse_itl_ms_p99': pct_ms(gaps, 0.99),
            # Robustness plane: degradation under --fault-plan /
            # admission control is A/B-able from the same JSON line.
            'fault_plan': bool(args.fault_plan),
            'shed_requests': shed[0],
            'server_requests_shed': serving.get('requests_shed'),
            'server_deadline_exceeded':
                serving.get('deadline_exceeded'),
            'engine_restarts': stats.get('engine_restarts'),
        }
        d_tokens = ((stats.get('tokens_committed') or 0) -
                    (stats0.get('tokens_committed') or 0))
        if stats.get('engine') == 'continuous':
            # Window-normalized scheduler health: stall seconds per
            # wall second / per generated token, and chunked-prefill
            # cadence — comparable across runs of different lengths.
            d_stall = ((stats.get('decode_stall_s') or 0.0) -
                       (stats0.get('decode_stall_s') or 0.0))
            d_chunks = ((stats.get('prefill_chunks_run') or 0) -
                        (stats0.get('prefill_chunks_run') or 0))
            record['decode_stall_s_window'] = round(d_stall, 4)
            record['decode_stall_s_per_s'] = round(
                d_stall / elapsed, 5)
            record['decode_stall_ms_per_token'] = round(
                1000.0 * d_stall / max(d_tokens, 1), 4)
            record['prefill_chunks_per_s'] = round(
                d_chunks / elapsed, 3)
        bpt = stats.get('attention_bytes_per_token')
        if bpt:
            # Roofline scoreboard: achieved per-chip tokens/s against
            # the analytic HBM bytes/token model the server exports
            # (ops/pallas_paged.bytes_per_token_model via /stats).
            # fraction_of_hbm_peak ~= how much of the memory roof the
            # decode loop actually sustains; on CPU it is a sanity
            # denominator, on TPU the tuning target.
            # bytes_per_token_model is already per-chip under stage
            # and tensor splits (each chip walks only its own stage's
            # layers / kv-head shard), so dividing tokens/s by the
            # full chip count keeps the roofline product honest.
            tokens_per_s = d_tokens / elapsed
            per_chip = tokens_per_s / (
                max(args.tensor, 1) *
                max(getattr(args, 'stages', 1), 1))
            bytes_per_s = per_chip * bpt['total_bytes_per_token']
            record['roofline'] = {
                'attention_impl': stats.get('attention_impl'),
                'bytes_per_token_model': bpt,
                'tokens_per_s': round(tokens_per_s, 2),
                'per_chip_tokens_per_s': round(per_chip, 2),
                'modeled_hbm_bytes_per_s_per_chip': round(
                    bytes_per_s, 1),
                'hbm_peak_gbps': args.hbm_peak_gbps,
                'fraction_of_hbm_peak': round(
                    bytes_per_s / (args.hbm_peak_gbps * 1e9), 8),
            }
        if adapter_dir:
            # Per-adapter req/s (client-side) + the registry's own
            # residency/eviction accounting (server-side).
            server_ad = stats.get('adapters') or {}
            record['adapters'] = {
                'n': args.adapters,
                'mix': args.adapter_mix if assignment else 'base-only',
                'rank': args.adapter_rank,
                'per_adapter': {
                    name: {'requests': n,
                           'req_per_sec': round(n / elapsed, 3)}
                    for name, n in sorted(adapter_counts.items())},
                'server_loads': server_ad.get('loads'),
                'server_evictions': server_ad.get('evictions'),
                'server_load_failures': server_ad.get('load_failures'),
                'server_requests': server_ad.get('requests'),
                'loaded_at_end': server_ad.get('loaded'),
                'bytes_per_adapter': server_ad.get(
                    'bytes_per_adapter'),
            }
        return record
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def _with(args, **over) -> argparse.Namespace:
    """A shallow copy of the parsed args with fields overridden (the
    A/B arms vary one knob over an otherwise identical workload)."""
    import copy
    arm = copy.copy(args)
    for key, val in over.items():
        setattr(arm, key, val)
    return arm


def run_quant_ab(args) -> dict:
    """The quantized-serving A/B (the committed BENCH_quant record):
    bf16 KV vs int8 KV at the SAME --kv-pool-bytes (int8 buys ~2x
    the pages — more slots / prefix residency per HBM byte), plus an
    int8-KV + int8-weights arm. Identical workload per arm."""
    runs = {
        'kv_bf16': _run_single(_with(args, kv_dtype='bf16',
                                     weight_dtype=None)),
        'kv_int8': _run_single(_with(args, kv_dtype='int8',
                                     weight_dtype=None)),
        'kv_int8_w_int8': _run_single(_with(args, kv_dtype='int8',
                                            weight_dtype='int8')),
    }
    base, q = runs['kv_bf16'], runs['kv_int8']
    return {
        'bench': 'serve_quant',
        'engine': args.engine,
        'model': args.model,
        'kv_pool_bytes': args.kv_pool_bytes,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'num_slots': args.num_slots,
        'shared_prefix': args.shared_prefix,
        'prefix_groups': max(1, args.prefix_groups or 1),
        # Same pool bytes -> int8 holds ~2x the pages: the
        # slots/residency headline (>= 1.8 is the acceptance gate).
        'kv_pages_ratio_int8_vs_bf16': round(
            q['kv_pages_total'] / max(base['kv_pages_total'], 1), 3),
        'req_per_sec_ratio_int8_vs_bf16': round(
            q['req_per_sec'] / max(base['req_per_sec'], 1e-9), 3),
        'runs': runs,
    }


def run_tensor_ab(args) -> dict:
    """--tensor 1 vs --tensor N over the identical workload: the
    per-chip decode-throughput scaling record (ROADMAP item 1's
    still-missing serve_bench deliverable; CPU runs fake the chips
    with XLA host devices).

    With --kv-pool-bytes set the A/B grows a POOL-CAPACITY axis
    (PR 15): the flag is per-chip, so both arms spend the same HBM
    per chip, and the sharded-pool arm should report ~Nx the TOTAL
    pages — the headline `pool_pages_ratio` — with fewer
    page-pressure preemptions and better prefix-cache residency at
    the same offered load."""
    n = max(2, args.tensor)
    runs = {
        'tensor_1': _run_single(_with(args, tensor=1)),
        f'tensor_{n}': _run_single(_with(args, tensor=n)),
    }
    base, tp = runs['tensor_1'], runs[f'tensor_{n}']
    out = {
        'bench': 'serve_tensor',
        'engine': args.engine,
        'model': args.model,
        'tensor': n,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'kv_dtype': args.kv_dtype or 'bf16',
        'weight_dtype': args.weight_dtype or 'bf16',
        'per_chip_ratio': round(
            tp['per_chip_req_per_sec'] /
            max(base['per_chip_req_per_sec'], 1e-9), 3),
        'runs': runs,
    }
    if args.kv_pool_bytes:
        out['kv_pool_bytes_per_chip'] = args.kv_pool_bytes
        out['pool_pages_ratio'] = round(
            (tp['kv_pages_total'] or 0) /
            max(base['kv_pages_total'] or 0, 1), 3)
        out['pool_capacity'] = {
            arm: {'kv_pages_total': rec['kv_pages_total'],
                  'kv_shard_ways': rec['kv_shard_ways'],
                  'kv_pool_bytes_per_device':
                      rec['kv_pool_bytes_per_device'],
                  'preemptions': rec['preemptions'],
                  'prefix_hit_rate': rec['prefix_hit_rate']}
            for arm, rec in runs.items()}
    return out


def run_pp_ab(args) -> dict:
    """TP-only vs TP x PP at EQUAL chip count over the identical
    greedy workload (the committed BENCH_tp_pp record): with
    --tensor T --stages S the arms are tensor=T*S/stages=1 and
    tensor=T/stages=S on the same T*S virtual chips. The staged arm
    splits the KV pool by LAYER RANGE on top of the kv-heads shard —
    --kv-pool-bytes is per chip, so at fixed per-chip HBM the staged
    pool holds ~S x the pages per shard group (`pool_pages_ratio`)
    — while the pipelined chunk stream prices prefill at the
    closed-form (S-1)/(M+S-1) fill/drain bubble and the S-deep
    decode ring keeps p99 ITL within a small factor of TP-only
    (`decode_itl_p99_ratio`; the acceptance gate is <= 1.25)."""
    s = max(2, args.stages)
    t = max(1, args.tensor)
    chips = s * t
    tp_arm, pp_arm = f'tp{chips}', f'tp{t}_pp{s}'
    runs = {
        tp_arm: _run_single(_with(args, tensor=chips, stages=1)),
        pp_arm: _run_single(_with(args, tensor=t, stages=s)),
    }
    base, pp = runs[tp_arm], runs[pp_arm]
    from skypilot_tpu.parallel.pipeline_schedule import (
        make_inference_schedule)
    base_roof = base.get('roofline') or {}
    pp_roof = pp.get('roofline') or {}
    out = {
        'bench': 'serve_tp_pp',
        'engine': args.engine,
        'model': args.model,
        'chips': chips,
        'tensor': t,
        'stages': s,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'kv_dtype': args.kv_dtype or 'bf16',
        # Headlines: per-chip decode throughput and tail ITL of the
        # staged arm relative to TP-only at the same chip count.
        'per_chip_req_ratio': round(
            pp['per_chip_req_per_sec'] /
            max(base['per_chip_req_per_sec'], 1e-9), 3),
        'per_chip_decode_tokens_ratio': round(
            (pp_roof.get('per_chip_tokens_per_s') or 0.0) /
            max(base_roof.get('per_chip_tokens_per_s') or 0.0, 1e-9),
            3),
        'decode_itl_p99_ratio': round(
            (pp['itl_ms_p99'] or 0.0) /
            max(base['itl_ms_p99'] or 0.0, 1e-9), 3),
        # The staged arm's measured last-burst bubble plus the
        # analytic (S-1)/(M+S-1) table it must sit in — read from
        # the schedule object, not re-derived here.
        'prefill_bubble_fraction': pp['prefill_bubble_fraction'],
        'prefill_bubble_closed_form': {
            f'microbatches_{m}': round(
                make_inference_schedule(s, m).bubble_fraction, 6)
            for m in (1, 2, 4, 8)},
        'runs': runs,
    }
    if args.kv_pool_bytes:
        out['kv_pool_bytes_per_chip'] = args.kv_pool_bytes
        out['pool_pages_ratio'] = round(
            (pp['kv_pages_total'] or 0) /
            max(base['kv_pages_total'] or 0, 1), 3)
        out['pool_capacity'] = {
            arm: {'kv_pages_total': rec['kv_pages_total'],
                  'kv_shard_ways': rec['kv_shard_ways'],
                  'kv_pool_bytes_per_device':
                      rec['kv_pool_bytes_per_device'],
                  'kv_pool_stages': rec['kv_pool_stages'],
                  'preemptions': rec['preemptions'],
                  'prefix_hit_rate': rec['prefix_hit_rate']}
            for arm, rec in runs.items()}
    return out


def run_disagg_ab(args) -> dict:
    """The disaggregation scoreboard (the committed BENCH_disagg
    record's `sweep` half): a long-prompt-fraction sweep over TWO
    stub fleets of equal total size — UNIFIED (every replica
    prefills its own prompts; long prefills hold the engine lock and
    stretch co-resident streams' inter-token gaps) vs DISAGGREGATED
    (long prompts route to a prefill pool that hands the KV chain to
    the decode pool; decode replicas never pay the prefill). Stub
    replicas make the engine-contention model deterministic on a
    1-core bench host; the real-engine bit-identity of the handoff
    and spill paths is pinned by tier-1 (test_kv_transfer.py)."""
    total = args.replicas + max(args.prefill_replicas, 1)
    fracs = [0.0, 0.25, 0.5]
    sweep = {'unified': {}, 'disagg': {}}
    for frac in fracs:
        unified = _run_fleet_once(
            _with(args, long_prompt_frac=frac, prefill_replicas=0,
                  replicas=total),
            args.lb_policy)
        disagg = _run_fleet_once(
            _with(args, long_prompt_frac=frac,
                  prefill_replicas=max(args.prefill_replicas, 1),
                  replicas=total - max(args.prefill_replicas, 1)),
            args.lb_policy)
        sweep['unified'][str(frac)] = unified
        sweep['disagg'][str(frac)] = disagg

    def ratio(runs):
        base = runs['0.0']['decode_itl_ms_p99'] or 1e-9
        return {frac: round((runs[frac]['decode_itl_ms_p99'] or 0.0)
                            / base, 3)
                for frac in runs}

    return {
        'bench': 'serve_disagg_sweep',
        'stub_replicas': True,
        'total_replicas': total,
        'prefill_replicas': max(args.prefill_replicas, 1),
        'disagg_prompt_threshold': args.disagg_prompt_threshold,
        'long_prompt_len': args.long_prompt_len,
        'long_prompt_fracs': fracs,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'stub_token_sleep_ms': args.stub_token_sleep_ms,
        'stub_prefill_ms_per_token': args.stub_prefill_ms_per_token,
        # p99 ITL at each fraction relative to that arm's frac=0
        # value: the acceptance gate is disagg <= 1.25 at every
        # fraction while unified degrades.
        'p99_itl_vs_frac0': {'unified': ratio(sweep['unified']),
                             'disagg': ratio(sweep['disagg'])},
        'sweep': sweep,
    }


def _storm_expected_tokens(seed: int, prompt_len: int,
                           max_new: int) -> list:
    """The stub's deterministic token row for a prompt of
    `prompt_len` under a FLEET-SHARED seed: the unmigrated control
    an evacuated stream must match bit-for-bit (stub.py's formula —
    tokens depend only on seed, prompt length, and position, never
    on which replica generates them)."""
    return [(seed * 1000003 + prompt_len * 31 + j) % 50000
            for j in range(max_new)]


def _run_storm_once(args, arm: str) -> dict:
    """One storm arm over a stub fleet: `control` (no fault plan),
    `migrate` (zone storm; preempted replicas evacuate KV chains to
    survivors inside the grace window), or `replay` (zone storm with
    --no-migrate: preemption aborts the replica mid-stream and the
    client retries from the full prompt). All replicas share one
    seed so a migrated continuation is bit-comparable against the
    client-side expected row."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import \
        load_balancing_policies  # noqa: F401 (registers policies)
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane import lb as lb_mod
    from skypilot_tpu.serve.replica_plane import replica_manager as rm
    from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY

    env = _server_env(args)
    if arm != 'control':
        # Stubs take no --fault-plan flag; the plan arms from the
        # child environment at import. The bench process itself
        # never sees it (os.environ is untouched).
        env['STPU_FAULT_PLAN'] = args.fault_plan
    extra = ['--cache-pages', str(args.stub_cache_pages),
             '--token-sleep-ms', str(args.stub_token_sleep_ms),
             # Fleet-shared seed (last --seed wins over the
             # factory's per-replica one): bit-identity across
             # migration is checkable against a closed form.
             '--seed', str(args.storm_seed)]
    if arm == 'replay':
        extra += ['--no-migrate']
    factory = rm.stub_factory(extra_args=extra, env=env)
    spec = spec_lib.SkyServiceSpec(min_replicas=args.replicas,
                                   max_replicas=args.replicas)
    autoscaler = autoscalers.EngineMetricsAutoscaler(spec)
    policy = LB_POLICY_REGISTRY.from_str(args.lb_policy)()
    # Preempted replicas are FAILED and then forgotten by the next
    # controller tick (terminal views are removed) — count them at
    # the lifecycle event, not from the end-of-run view list.
    preempted = [0]

    def on_event(name: str, view) -> None:
        if name == 'dead' and getattr(view, 'zone', '') == \
                args.storm_zone:
            preempted[0] += 1

    manager = ReplicaManager(factory, drain_grace_s=30.0,
                             scrape_timeout_s=20.0,
                             max_scrape_failures=1000,
                             on_event=on_event)
    # Tight tick: a preempted replica must leave the routing set
    # (and its replacement arrive) within a fraction of the storm.
    controller = FleetController(manager, policy, autoscaler,
                                 interval_s=0.5)
    lb_port = _free_port()
    lb = make_lb_server(policy, lb_port, policy_name=args.lb_policy,
                        manager=manager)
    lb_thread = threading.Thread(target=lb.serve_forever, daemon=True)
    lb_thread.start()
    url = f'http://127.0.0.1:{lb_port}'
    try:
        # First --storm-spot replicas carry the storm zone; the rest
        # are the on-demand survivors chains evacuate to.
        for i in range(args.replicas):
            zone = args.storm_zone if i < args.storm_spot else ''
            manager.spawn(zone=zone)
        if not controller.wait_ready(args.replicas, timeout_s=120):
            raise RuntimeError(
                f'storm fleet of {args.replicas} not ready')
        controller.tick()  # push peer sets before traffic
        ticker = threading.Thread(target=controller.run, daemon=True)
        ticker.start()

        rng = random.Random(0)
        prompts = [[rng.randrange(1, 50000)
                    for _ in range(rng.randrange(4, 16))]
                   for _ in range(args.requests)]
        latencies = []
        itl_gaps = []
        errors = [0]        # final (unrecovered) 5xx / transport
        retries = [0]       # replay-arm full-prompt resubmissions
        recomputed = [0]    # client-visible recompute: prompt +
        #                     already-received tokens per retry
        mismatches = [0]    # completed rows != closed-form control
        shed = [0]
        lock = threading.Lock()
        queue = list(enumerate(prompts))

        def client() -> None:
            while True:
                with lock:
                    if not queue:
                        return
                    _idx, prompt = queue.pop()
                expected = _storm_expected_tokens(
                    args.storm_seed, len(prompt),
                    args.max_new_tokens)
                t0 = time.perf_counter()
                attempt = 0
                while True:
                    attempt += 1
                    ttft = None
                    last_t = None
                    gaps = []
                    toks = []
                    failed = False
                    try:
                        with requests.post(f'{url}/generate', json={
                                'tokens': [prompt],
                                'max_new_tokens':
                                    args.max_new_tokens,
                                'stream': True}, timeout=600,
                                stream=True) as resp:
                            if resp.status_code == 429:
                                with lock:
                                    shed[0] += 1
                                break
                            if resp.status_code >= 500:
                                failed = True
                            else:
                                done = False
                                # chunk_size=1: SSE frames are a
                                # few dozen bytes; default chunking
                                # batches whole bursts into one
                                # read and flattens every gap to 0.
                                for raw in resp.iter_lines(
                                        chunk_size=1):
                                    if not raw.startswith(b'data: '):
                                        continue
                                    if raw == b'data: [DONE]':
                                        done = True
                                        break
                                    frame = json.loads(raw[6:])
                                    if 'token' in frame:
                                        now = time.perf_counter()
                                        if ttft is None:
                                            ttft = now - t0
                                        if last_t is not None:
                                            gaps.append(now - last_t)
                                        last_t = now
                                        toks.append(
                                            int(frame['token']))
                                if not done:
                                    # Connection died mid-stream
                                    # (preempted replica).
                                    failed = True
                    except requests.RequestException:
                        failed = True
                    if not failed:
                        total = time.perf_counter() - t0
                        with lock:
                            latencies.append(
                                (ttft if ttft is not None else total,
                                 total))
                            itl_gaps.extend(gaps)
                            if toks != expected:
                                mismatches[0] += 1
                        break
                    # A failed attempt restarts from the raw prompt:
                    # the server must re-prefill it AND regenerate
                    # every token the client already held — the
                    # replay arm's whole cost model.
                    with lock:
                        recomputed[0] += len(prompt) + len(toks)
                    if attempt > 5:
                        with lock:
                            errors[0] += 1
                        break
                    with lock:
                        retries[0] += 1
                    time.sleep(0.5)

        start = time.perf_counter()
        threads = [threading.Thread(target=client)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start

        manager.scrape_once()
        views = sorted(manager.views(), key=lambda v: v.replica_id)
        migration = lb_mod.merge_migration_stats(views)
        # The sender's evacuation counters die with its process (it
        # exits after the grace window, before a final scrape);
        # receivers' migrations_in is the durable session count.
        sessions_evac = max(
            int(migration.get('sessions_evacuated', 0) or 0),
            int(migration.get('migrations_in', 0) or 0))
        server_recomputed = int(migration.get('tokens_recomputed', 0)
                                or 0)
        # Per-disrupted-session recompute: the migrate arm pays the
        # sub-page remainder the chain keys could not cover; the
        # replay arm pays the full prompt + lost tokens per retry.
        if arm == 'replay':
            per_session = (recomputed[0] / retries[0]
                           if retries[0] else 0.0)
        else:
            per_session = (server_recomputed / sessions_evac
                           if sessions_evac else 0.0)
        ttfts = sorted(l[0] for l in latencies)
        gaps_sorted = sorted(itl_gaps)
        return {
            'arm': arm,
            'replicas': args.replicas,
            'spot_replicas': args.storm_spot,
            'storm_zone': args.storm_zone,
            'requests': len(latencies),
            'client_errors': errors[0],
            'client_retries': retries[0],
            'shed_requests': shed[0],
            'token_mismatches': mismatches[0],
            'replicas_preempted': preempted[0],
            'sessions_migrated': sessions_evac,
            'req_per_sec': round(len(latencies) / elapsed, 2),
            'p50_ttft_ms': pct_ms(ttfts, 0.50),
            'p99_ttft_ms': pct_ms(ttfts, 0.99),
            'sse_itl_ms_p50': pct_ms(gaps_sorted, 0.50),
            'sse_itl_ms_p99': pct_ms(gaps_sorted, 0.99),
            'migration': migration,
            'tokens_recomputed_client': recomputed[0],
            'tokens_recomputed_server': server_recomputed,
            'tokens_recomputed_per_preempted_session': round(
                per_session, 2),
        }
    finally:
        controller.shutdown()
        lb.shutdown()


def run_storm_ab(args) -> dict:
    """The spot-storm A/B (the committed BENCH_migrate record):
    the IDENTICAL workload through three stub fleets — no storm
    (control), a zone storm answered by live KV-chain migration,
    and the same storm with migration disabled (full replay from
    the prompt). Headlines: tokens recomputed per preempted
    session (~0 for migration vs prompt+lost-tokens for replay),
    zero client 5xx in the migration arm, and every completed row
    bit-identical to the closed-form unmigrated control."""
    runs = {
        'control': _run_storm_once(args, 'control'),
        'migrate': _run_storm_once(args, 'migrate'),
        'replay': _run_storm_once(args, 'replay'),
    }
    mig, rep = runs['migrate'], runs['replay']
    return {
        'bench': 'serve_storm',
        'stub_replicas': True,
        'replicas': args.replicas,
        'spot_replicas': args.storm_spot,
        'storm_zone': args.storm_zone,
        'fault_plan': args.fault_plan,
        'requests': args.requests,
        'concurrency': args.concurrency,
        'max_new_tokens': args.max_new_tokens,
        'stub_token_sleep_ms': args.stub_token_sleep_ms,
        'storm_seed': args.storm_seed,
        'migrate_zero_5xx': mig['client_errors'] == 0,
        'migrate_outputs_bit_identical':
            mig['token_mismatches'] == 0,
        'tokens_recomputed_per_preempted_session': {
            'migrate': mig['tokens_recomputed_per_preempted_session'],
            'replay': rep['tokens_recomputed_per_preempted_session'],
        },
        'p99_itl_ms': {name: r['sse_itl_ms_p99']
                       for name, r in runs.items()},
        'runs': runs,
    }


def run_spill_ab(args) -> dict:
    """The tiered-cache A/B (the committed BENCH_disagg record's
    `spill` half): the SAME multi-session workload against a
    pool-pressured llama-tiny server with and without the host-RAM
    spill tier. Without it, every pool-pressure eviction recomputes
    the prefix on the next hit; with it, the pages restore
    bit-identically (tier-1 pins the bit-identity) — the prefix hit
    rate must be strictly higher."""
    runs = {
        'no_spill': _run_single(_with(args, kv_spill_bytes=0)),
        'spill': _run_single(_with(
            args,
            kv_spill_bytes=args.kv_spill_bytes or 256 * 1024 * 1024)),
    }
    base = runs['no_spill']
    tier = runs['spill']
    return {
        'bench': 'serve_spill',
        'engine': args.engine,
        'model': args.model,
        'kv_pool_bytes': args.kv_pool_bytes,
        'kv_spill_bytes': (args.kv_spill_bytes or
                           256 * 1024 * 1024),
        'requests': args.requests,
        'concurrency': args.concurrency,
        'shared_prefix': args.shared_prefix,
        'prefix_groups': max(1, args.prefix_groups or 1),
        'prefix_hit_rate_no_spill': base.get('prefix_hit_rate'),
        'prefix_hit_rate_spill': tier.get('prefix_hit_rate'),
        'evictions_no_spill': base.get('prefix_evictions'),
        'restored_pages': ((tier.get('kv_spill') or {})
                           .get('restored_pages')),
        'runs': runs,
    }


def _run_kernel_arm(args, impl, adapter_dir, names) -> dict:
    """One --kernel-ab arm: boot serve_lm pinned to `impl` (via
    SKYPILOT_TPU_PAGED_IMPL), run the deterministic greedy workload
    NON-streamed (exact token rows back), return tokens + the
    server's resolved impl and bytes/token model."""
    arm = _with(args, paged_impl=impl)
    port = _free_port()
    cmd = _build_server_cmd(arm, adapter_dir) + ['--port', str(port)]
    server = subprocess.Popen(cmd, env=_server_env(arm),
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.STDOUT)
    url = f'http://127.0.0.1:{port}'
    try:
        deadline = time.time() + 300
        info = None
        while time.time() < deadline:
            try:
                info = requests.get(url, timeout=2).json()
                break
            except requests.RequestException:
                time.sleep(1)
                if server.poll() is not None:
                    raise RuntimeError('serve_lm died')
        if info is None:
            raise RuntimeError('serve_lm not ready within 300s')
        vocab = int(info['vocab_size'])
        rng = random.Random(0)
        prompts = [[rng.randrange(1, vocab)
                    for _ in range(rng.randrange(4, 16))]
                   for _ in range(args.requests)]
        # Round-robin over base + every adapter: the fused QKV LoRA
        # path and the base fast path both sit in the comparison.
        targets = [None] + list(names)
        t0 = time.perf_counter()
        token_rows = []
        for i, p in enumerate(prompts):
            body = {'tokens': [p],
                    'max_new_tokens': args.max_new_tokens}
            tgt = targets[i % len(targets)]
            if tgt:
                body['model'] = tgt
            resp = requests.post(f'{url}/generate', json=body,
                                 timeout=600)
            resp.raise_for_status()
            token_rows.append(resp.json()['tokens'][0])
        elapsed = time.perf_counter() - t0
        stats = requests.get(f'{url}/stats', timeout=30).json()
        return {
            'impl_requested': impl,
            'impl_resolved': stats.get('attention_impl'),
            'kv_dtype': (stats.get('storage') or {}).get('kv_dtype'),
            'requests': len(token_rows),
            'elapsed_s': round(elapsed, 2),
            'bytes_per_token_model':
                stats.get('attention_bytes_per_token'),
            'tokens': token_rows,
        }
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def run_kernel_ab(args) -> dict:
    """The fused-kernel A/B (the committed BENCH_kernel record): the
    IDENTICAL int8-KV + multi-LoRA greedy workload against a server
    on the fused interpret-mode Pallas path vs the XLA
    dequantize-and-gather path. The record asserts the acceptance
    gates itself: byte-identical greedy tokens, strictly fewer
    modeled HBM bytes/token on the fused path (the dequantized
    [T,Hq,D] materialization it deletes), and the 3->1 QKV LoRA
    dispatch fusion."""
    import hashlib
    import tempfile
    from skypilot_tpu.ops import pallas_paged as pp

    adapter_dir = tempfile.mkdtemp(prefix='serve_bench_kernel_')
    names = _make_adapter_artifacts(args, adapter_dir)
    arms = {impl: _run_kernel_arm(args, impl, adapter_dir, names)
            for impl in ('fused_interpret', 'xla')}
    fused, xla = arms['fused_interpret'], arms['xla']

    identical = fused['tokens'] == xla['tokens']
    assert identical, (
        'fused kernel diverged from the XLA reference on greedy '
        'tokens — the bit-identity acceptance gate failed')
    fb = fused['bytes_per_token_model']
    xb = xla['bytes_per_token_model']
    assert (fb['total_bytes_per_token'] <
            xb['total_bytes_per_token']), (
        'fused path must model strictly fewer HBM bytes/token than '
        'the XLA dequantize route at int8')
    digest = hashlib.sha256(
        json.dumps(fused['tokens']).encode()).hexdigest()[:16]
    for rec in arms.values():
        rec['tokens_sha256_16'] = digest
        rec['tokens_sample'] = rec['tokens'][0]
        del rec['tokens']      # the digest pins identity; keep the
        #                        committed record readable
    return {
        'bench': 'serve_kernel',
        'engine': args.engine,
        'model': args.model,
        'kv_dtype': 'int8',
        'adapters': args.adapters,
        'adapter_rank': args.adapter_rank,
        'requests': args.requests,
        'max_new_tokens': args.max_new_tokens,
        'greedy_tokens_bit_identical': identical,
        'modeled_bytes_per_token': {
            'fused_interpret': fb['total_bytes_per_token'],
            'xla': xb['total_bytes_per_token'],
        },
        'hbm_bytes_per_token_saved_frac': round(
            1.0 - fb['total_bytes_per_token'] /
            xb['total_bytes_per_token'], 4),
        'dequant_materialize_bytes_deleted':
            xb['dequant_materialize_bytes'],
        'qkv_lora_dispatches_per_layer': {
            'fused_interpret':
                pp.qkv_lora_dispatches_per_layer('fused_interpret'),
            'xla': pp.qkv_lora_dispatches_per_layer('xla'),
        },
        'runs': arms,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--engine', choices=['continuous', 'simple'],
                        default='continuous')
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--requests', type=int, default=64)
    parser.add_argument('--concurrency', type=int, default=8)
    parser.add_argument('--max-total-len', type=int, default=64)
    parser.add_argument('--max-new-tokens', type=int, default=24)
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--speculative', type=int, default=0,
                        metavar='K', help='prompt-lookup speculation '
                        '(works with both engines)')
    parser.add_argument('--decode-chunk', type=int, default=1,
                        metavar='N',
                        help='continuous engine: N decode steps per '
                             'dispatch (dispatch-overhead '
                             'amortization)')
    parser.add_argument('--long-prompt-frac', type=float, default=0.0,
                        metavar='F',
                        help='fraction of requests carrying a LONG '
                             'prompt (near max-total-len minus the '
                             'generation budget) mixed into the short '
                             'workload — the regime where whole-'
                             'prompt prefill stalls inter-token '
                             'latency and chunked prefill should not')
    parser.add_argument('--prefill-chunk', type=int, default=None,
                        metavar='C',
                        help='forwarded to serve_lm --prefill-chunk '
                             '(0 disables chunked prefill for A/B '
                             'runs; default: server default)')
    parser.add_argument('--prefill-budget', type=int, default=None,
                        metavar='T',
                        help='forwarded to serve_lm --prefill-budget')
    parser.add_argument('--no-pipeline-decode', action='store_true',
                        help='forwarded to serve_lm (disables '
                             'host/device decode pipelining)')
    parser.add_argument('--fault-plan', default=None, metavar='JSON',
                        help='forwarded to serve_lm --fault-plan '
                             '(inline JSON or a file path): run the '
                             'workload under injected faults and A/B '
                             'the JSON line against a clean run')
    parser.add_argument('--request-timeout', type=float, default=None,
                        help='forwarded to serve_lm '
                             '--request-timeout')
    parser.add_argument('--max-queue-requests', type=int, default=None,
                        help='forwarded to serve_lm '
                             '--max-queue-requests (shed + 429 when '
                             'saturated; shed count lands in the '
                             'JSON line)')
    parser.add_argument('--max-queue-tokens', type=int, default=None,
                        help='forwarded to serve_lm '
                             '--max-queue-tokens')
    parser.add_argument('--replicas', type=int, default=0,
                        metavar='N',
                        help='multi-replica mode: N serve_lm '
                             'processes behind the replica-plane LB '
                             '(serve/replica_plane/); the JSON line '
                             'gains a per-replica breakdown + '
                             'affinity hit ratio. 0 = single server')
    parser.add_argument('--lb-policy', default='prefix_affinity',
                        help='replica-plane LB policy '
                             '(prefix_affinity | round_robin | '
                             'least_load)')
    parser.add_argument('--ab-policies', action='store_true',
                        help='run the identical fleet workload under '
                             'prefix_affinity AND round_robin and '
                             'emit one combined JSON object (the '
                             'committed BENCH_serve_fleet record)')
    parser.add_argument('--prefix-groups', type=int, default=None,
                        metavar='G',
                        help='number of DISTINCT shared system '
                             'prompts (sessions) under '
                             '--shared-prefix. Fleet mode (default '
                             '8): affinity pins each group to one '
                             'replica while round-robin caches every '
                             'group everywhere. Single-server mode '
                             '(default 1): >1 exercises prefix-cache '
                             'RESIDENCY — the regime int8 KV pages '
                             'double')
    parser.add_argument('--stub-replicas', action='store_true',
                        help='fleet mode with model-free stub '
                             'replicas (replica_plane/stub.py): '
                             'deterministic control-plane smoke, no '
                             'XLA — the tier-1 CI mode')
    parser.add_argument('--stub-cache-pages', type=int, default=64,
                        help='stub replica prefix-cache capacity '
                             '(pages); bound it below the working '
                             'set to make prefix duplication '
                             'measurable')
    parser.add_argument('--stub-token-sleep-ms', type=float,
                        default=1.0,
                        help='stub replica per-token engine-lock '
                             'hold (the decode cadence)')
    parser.add_argument('--stub-prefill-ms-per-token', type=float,
                        default=0.0,
                        help='stub replica simulated prefill cost '
                             'per missed prompt token (held in '
                             'page-sized engine-lock chunks — the '
                             'contention long prompts inflict on '
                             'co-resident decode streams)')
    parser.add_argument('--prefill-replicas', type=int, default=0,
                        metavar='N',
                        help='fleet mode: N additional prefill-role '
                             'replicas (disaggregated serving); '
                             'long prompts route to them and hand '
                             'their KV chains to the decode pool')
    parser.add_argument('--disagg-prompt-threshold', type=int,
                        default=256, metavar='T',
                        help='LB prompt-length threshold (tokens) '
                             'for routing to the prefill pool')
    parser.add_argument('--long-prompt-len', type=int, default=0,
                        metavar='L',
                        help='token length of --long-prompt-frac '
                             'prompts (0 = derived from '
                             '--max-total-len; set explicitly for '
                             'stub fleets, which have no real '
                             'context limit)')
    parser.add_argument('--kv-spill-bytes', type=int, default=0,
                        metavar='B',
                        help='forwarded to serve_lm '
                             '--kv-spill-bytes (tiered prefix '
                             'cache: evicted pages spill to host '
                             'RAM and restore on hit)')
    parser.add_argument('--kv-cold-dir', default=None, metavar='DIR',
                        help='forwarded to serve_lm --kv-cold-dir')
    parser.add_argument('--disagg-ab', action='store_true',
                        help='run the long-prompt-fraction sweep '
                             '{0, 0.25, 0.5} over equal-size '
                             'UNIFIED vs DISAGGREGATED stub fleets '
                             'and emit one combined JSON object '
                             '(the committed BENCH_disagg sweep). '
                             'Implies --stub-replicas')
    parser.add_argument('--storm-ab', action='store_true',
                        help='run the identical workload through a '
                             'control fleet, a zone-storm fleet '
                             'answering preemptions with live '
                             'KV-chain migration, and a --no-migrate '
                             'full-replay fleet, and emit one '
                             'combined JSON object (the committed '
                             'BENCH_migrate record). Implies '
                             '--stub-replicas; needs --fault-plan '
                             '(default: examples/fault_plans/'
                             'decode_zone_storm.json)')
    parser.add_argument('--storm-zone', default='us-east5-b',
                        help='zone the storm plan scopes to; the '
                             'first --storm-spot replicas carry it')
    parser.add_argument('--storm-spot', type=int, default=1,
                        help='how many replicas are spot (zoned) — '
                             'the preemption victims')
    parser.add_argument('--storm-seed', type=int, default=2026,
                        help='FLEET-SHARED stub seed: migrated '
                             'outputs are checked bit-for-bit '
                             'against the closed-form control row')
    parser.add_argument('--spill-ab', action='store_true',
                        help='run the identical pool-pressured '
                             'workload with and without the '
                             'host-RAM spill tier and emit one '
                             'combined JSON object (the committed '
                             'BENCH_disagg spill record). '
                             'Single-server llama-tiny mode; use '
                             'with --kv-pool-bytes + '
                             '--shared-prefix + --prefix-groups')
    parser.add_argument('--state-dir', default=None, metavar='DIR',
                        help='fleet mode: journal replica lifecycle '
                             'to DIR/<policy>/fleet.journal (the '
                             'crash-only controller contract; see '
                             'serve_fleet --state-dir)')
    parser.add_argument('--adapters', type=int, default=0,
                        metavar='N',
                        help='multi-LoRA mode (single-server): '
                             'generate N random adapter artifacts, '
                             'start serve_lm with --adapter-dir, and '
                             'target adapters per request via the '
                             '`model` field (assignment from '
                             '--adapter-mix, deterministic)')
    parser.add_argument('--adapter-mix', default='zipf',
                        choices=['zipf', 'uniform'],
                        help='per-request adapter assignment: zipf '
                             '(weight 1/(k+1) — few hot tenants, '
                             'exercises LRU churn) or uniform')
    parser.add_argument('--adapter-rank', type=int, default=8,
                        help='rank of the generated bench adapters')
    parser.add_argument('--max-adapters', type=int, default=8,
                        help='forwarded to serve_lm --max-adapters '
                             '(clamped up to --adapters)')
    parser.add_argument('--adapter-ab', action='store_true',
                        help='run the adapter-mix workload AND an '
                             'all-base workload against identically '
                             'configured servers (adapters loaded '
                             'but untargeted = the zero-overhead '
                             'fast path) and emit one combined JSON '
                             'object (the committed BENCH_lora '
                             'record)')
    parser.add_argument('--repetitive', action='store_true',
                        help='structured (repeated-trigram) prompts — '
                             'the regime speculation accelerates')
    parser.add_argument('--shared-prefix', type=int, default=0,
                        metavar='N',
                        help='prepend one shared N-token system '
                             'prompt to every request — the regime '
                             'prefix caching accelerates (chatbots, '
                             'few-shot templates)')
    parser.add_argument('--no-prefix-caching', action='store_true')
    parser.add_argument('--kv-dtype', choices=['bf16', 'int8'],
                        default=None,
                        help='forwarded to serve_lm --kv-dtype '
                             '(int8 KV pages; default: server '
                             'default bf16)')
    parser.add_argument('--kv-pool-bytes', type=int, default=0,
                        metavar='B',
                        help='forwarded to serve_lm --kv-pool-bytes: '
                             'size the KV pool by DEVICE BYTES so '
                             'bf16/int8 arms spend the same HBM')
    parser.add_argument('--weight-dtype', choices=['bf16', 'int8'],
                        default=None,
                        help='forwarded to serve_lm --weight-dtype '
                             '(int8 per-channel projection weights)')
    parser.add_argument('--tensor', type=int, default=1,
                        help='forwarded to serve_lm --tensor N '
                             '(tensor-parallel serving); on CPU the '
                             'bench arms the server with '
                             'XLA_FLAGS=--xla_force_host_platform_'
                             'device_count=N. The JSON line gains '
                             'per_chip_req_per_sec')
    parser.add_argument('--stages', type=int, default=1,
                        help='forwarded to serve_lm --stages S '
                             '(pipeline-parallel serving over S '
                             'stages; total chips = S x --tensor). '
                             'Needs --engine continuous; per-chip '
                             'normalization divides by the full '
                             '(stage, tensor) mesh')
    parser.add_argument('--quant-ab', action='store_true',
                        help='run bf16-KV vs int8-KV (same '
                             '--kv-pool-bytes) vs int8-KV+int8-'
                             'weights over the identical workload '
                             'and emit one combined JSON object '
                             '(the committed BENCH_quant record). '
                             'Requires --kv-pool-bytes')
    parser.add_argument('--paged-impl', default=None,
                        choices=['auto', 'xla', 'kernel', 'fused',
                                 'fused_interpret'],
                        help='pin the server\'s paged-attention '
                             'implementation (exported as '
                             'SKYPILOT_TPU_PAGED_IMPL; see '
                             'ops/pallas_paged.py)')
    parser.add_argument('--hbm-peak-gbps', type=float, default=2765.0,
                        metavar='GBPS',
                        help='per-chip HBM peak bandwidth for the '
                             'roofline block (default: TPU v5p '
                             '2765 GB/s; on CPU the fraction is a '
                             'sanity denominator only)')
    parser.add_argument('--kernel-ab', action='store_true',
                        help='run the identical int8-KV + multi-LoRA '
                             'greedy workload on the fused '
                             'interpret-mode Pallas path AND the XLA '
                             'path, assert byte-identical tokens + '
                             'the modeled HBM and dispatch deltas, '
                             'and emit one combined JSON object (the '
                             'committed BENCH_kernel record). '
                             'Requires --adapters N')
    parser.add_argument('--tensor-ab', action='store_true',
                        help='run --tensor 1 vs --tensor N over the '
                             'identical workload and emit one '
                             'combined JSON object (per-chip req/s '
                             'scaling)')
    parser.add_argument('--pp-ab', action='store_true',
                        help='run TP-only (tensor=T*S) vs TP x PP '
                             '(tensor=T, stages=S) at EQUAL chip '
                             'count over the identical greedy '
                             'workload and emit one combined JSON '
                             'object (the committed BENCH_tp_pp '
                             'record: per-chip decode tokens/s, '
                             'TTFT, closed-form prefill bubble, '
                             'per-stage pool capacity). Requires '
                             '--stages >= 2')
    parser.add_argument('--hf', default=None,
                        help='serve a local HF checkpoint directory')
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--slo', default=None, metavar='SPEC',
                        help='score the run against declarative SLO '
                             'targets (dim=target,... over '
                             'p99_ttft_ms / p99_itl_ms / error_rate '
                             '/ shed_rate) and attach a machine-'
                             'checkable `slo` block: per-dimension '
                             'pass/fail + budget_consumed '
                             '(observed/target)')
    parser.add_argument('--cpu', action='store_true',
                        help='pin the server to the CPU backend')
    args = parser.parse_args()
    slo_targets = None
    if args.slo:
        from skypilot_tpu.observability import slo as slo_lib
        try:
            slo_targets = slo_lib.parse_slo(args.slo)
        except ValueError as exc:
            parser.error(str(exc))

    def _emit(record: dict) -> None:
        if slo_targets:
            attach_slo(record, slo_targets)
        print(json.dumps(record))

    if args.decode_chunk > 1 and args.engine != 'continuous':
        parser.error('--decode-chunk is a continuous-engine knob; '
                     'the one-shot engine would silently ignore it '
                     '(and the A/B record would lie)')
    if args.stub_replicas and not args.replicas:
        parser.error('--stub-replicas needs --replicas N')
    if args.adapter_ab and not args.adapters:
        parser.error('--adapter-ab needs --adapters N')
    if args.adapters and args.replicas:
        parser.error('--adapters is a single-server mode (fleet '
                     'replicas share no adapter workload assignment)')
    if args.adapters and args.engine != 'continuous':
        parser.error('--adapters needs --engine continuous (batched '
                     'per-slot LoRA lives in the slot engine)')
    if args.quant_ab and not args.kv_pool_bytes:
        parser.error('--quant-ab needs --kv-pool-bytes B (the A/B '
                     'holds pool BYTES constant; page counts follow '
                     'the storage format)')
    if (args.kv_dtype == 'int8' or args.quant_ab) and \
            args.engine != 'continuous':
        parser.error('--kv-dtype int8 needs --engine continuous '
                     '(int8 pages live in the paged slot engine)')
    if args.quant_ab and (args.replicas or args.adapters):
        parser.error('--quant-ab is a single-server mode')
    if args.tensor_ab and (args.replicas or args.adapters):
        parser.error('--tensor-ab is a single-server mode')
    if args.pp_ab:
        if args.replicas or args.adapters:
            parser.error('--pp-ab is a single-server mode')
        if args.stages < 2:
            parser.error('--pp-ab needs --stages >= 2 (the staged '
                         'arm runs tensor x stages; the TP-only arm '
                         'spends the same chips on tensor alone)')
        if args.engine != 'continuous':
            parser.error('--pp-ab needs --engine continuous '
                         '(pipeline-stage dispatch lives in the '
                         'paged slot engine)')
    if args.stages > 1 and args.engine != 'continuous':
        parser.error('--stages needs --engine continuous (serve_lm '
                     '--stages requires --continuous-batching)')

    if args.disagg_ab:
        if args.spill_ab or args.adapters or args.quant_ab:
            parser.error('--disagg-ab composes only with fleet '
                         'knobs (it runs its own stub fleets)')
        args.stub_replicas = True
        if not args.replicas:
            args.replicas = 2
        if not args.long_prompt_len:
            args.long_prompt_len = 512
        _emit(run_disagg_ab(args))
        return
    if args.storm_ab:
        if args.adapters or args.quant_ab or args.disagg_ab:
            parser.error('--storm-ab composes only with fleet '
                         'knobs (it runs its own stub fleets)')
        args.stub_replicas = True
        if not args.replicas:
            args.replicas = 3
        if args.replicas < 2:
            parser.error('--storm-ab needs --replicas >= 2 (the '
                         'storm victims must have survivors to '
                         'evacuate to)')
        if not args.fault_plan:
            args.fault_plan = os.path.join(
                REPO, 'examples', 'fault_plans',
                'decode_zone_storm.json')
        _emit(run_storm_ab(args))
        return
    if args.spill_ab:
        if args.replicas or args.adapters:
            parser.error('--spill-ab is a single-server mode')
        if args.engine != 'continuous':
            parser.error('--spill-ab needs --engine continuous (the '
                         'spill tier lives in the paged slot '
                         'engine)')
        _emit(run_spill_ab(args))
        return

    if args.kernel_ab:
        if args.replicas or args.quant_ab or args.tensor_ab:
            parser.error('--kernel-ab is a single-server mode')
        if not args.adapters:
            parser.error('--kernel-ab needs --adapters N (the fused '
                         'QKV LoRA path must sit in the comparison)')
        if args.engine != 'continuous':
            parser.error('--kernel-ab needs --engine continuous')
        _emit(run_kernel_ab(_with(args, kv_dtype='int8')))
        return

    if args.quant_ab:
        _emit(run_quant_ab(args))
        return
    if args.tensor_ab:
        _emit(run_tensor_ab(args))
        return
    if args.pp_ab:
        _emit(run_pp_ab(args))
        return

    if args.replicas:
        _emit(run_fleet(args))
        return

    if args.adapters:
        import tempfile
        adapter_dir = tempfile.mkdtemp(prefix='serve_bench_lora_')
        names = _make_adapter_artifacts(args, adapter_dir)
        assignment = _adapter_assignment(args, names)
        if args.adapter_ab:
            _emit({
                'bench': 'serve_lora',
                'engine': args.engine,
                'model': args.model,
                'adapters': args.adapters,
                'adapter_mix': args.adapter_mix,
                'adapter_rank': args.adapter_rank,
                'max_adapters': max(args.max_adapters, args.adapters),
                'requests': args.requests,
                'concurrency': args.concurrency,
                'runs': {
                    # adapters loaded AND targeted (the LoRA lanes)
                    'lora_mix': _run_single(args, adapter_dir,
                                            assignment),
                    # adapters configured, every request hits base:
                    # the zero-overhead fast path...
                    'base_only': _run_single(args, adapter_dir, None),
                    # ...measured against a server with no adapter
                    # registry at all (the pre-LoRA control arm).
                    'no_adapters': _run_single(args),
                },
            })
        else:
            _emit(_run_single(args, adapter_dir, assignment))
        return

    _emit(_run_single(args))



if __name__ == '__main__':
    main()
