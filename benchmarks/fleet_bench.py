#!/usr/bin/env python
"""Fleet-scale spot-orchestration bench: a preemption storm over N
concurrent managed jobs (ROADMAP open item 5).

Runs N simulated managed jobs through the REAL
JobController/StrategyExecutor recovery path (stubbed cloud, virtual
time — see skypilot_tpu/robustness/fleet_sim.py) under a zone-storm
fault plan, three times:

  1. jittered backoff, the shipped configuration;
  2. jittered again with the same seed — the two summaries must be
     BYTE-IDENTICAL (the determinism contract);
  3. jitter disabled — the thundering-herd control arm.

and asserts the acceptance invariants before writing the JSON:

  - every storm-hit job recovered through the checkpoint-resume
    path (status SUCCEEDED, all recovery events closed);
  - max concurrent relaunch attempts with jitter is strictly below
    the no-jitter herd peak (both read from the emitted
    relaunch-concurrency histogram).

Usage:

  python benchmarks/fleet_bench.py --jobs 500 --seed 2026 \
      --plan examples/fault_plans/zone_storm.json \
      --out BENCH_fleet_r06.json

The output JSON is a pure function of (args, plan): re-running with
the same seed and plan reproduces it byte for byte.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument('--jobs', type=int, default=500)
    parser.add_argument('--seed', type=int, default=2026)
    parser.add_argument('--plan', default=None, metavar='JSON',
                        help='storm fault plan (inline JSON or a '
                             'file path); default: the canonical '
                             'zone-storm scenario '
                             '(examples/fault_plans/zone_storm.json)')
    parser.add_argument('--accelerator', default='tpu-v5e-16')
    parser.add_argument('--storm-frac', type=float, default=0.6,
                        help='fraction of the fleet initially '
                             'placed in the storm zone')
    parser.add_argument('--work-s', type=float, default=120.0,
                        help='virtual seconds of useful work per job')
    parser.add_argument('--ckpt-every-s', type=float, default=30.0,
                        help='checkpoint cadence (lost-work '
                             'granularity on preemption)')
    parser.add_argument('--launch-duration-s', type=float,
                        default=4.0,
                        help='virtual provisioning time per launch '
                             '(what makes concurrent attempts '
                             'overlap)')
    parser.add_argument('--out', default=None, metavar='PATH',
                        help='write the JSON here (default: stdout '
                             'only)')
    parser.add_argument('--no-assert', action='store_true',
                        help='emit the JSON even when the '
                             'acceptance invariants fail (debugging '
                             'new scenarios)')
    args = parser.parse_args()

    from skypilot_tpu.robustness import fleet_sim

    if args.plan is None:
        plan_spec = fleet_sim.default_storm_plan()
    elif args.plan.lstrip().startswith('{'):
        plan_spec = json.loads(args.plan)
    else:
        with open(args.plan, 'r', encoding='utf-8') as f:
            plan_spec = json.load(f)

    def run(jitter: bool):
        return fleet_sim.FleetSim(
            num_jobs=args.jobs, plan_spec=plan_spec, seed=args.seed,
            accelerator=args.accelerator, work_s=args.work_s,
            ckpt_every_s=args.ckpt_every_s,
            launch_duration_s=args.launch_duration_s,
            storm_frac=args.storm_frac, jitter=jitter).run()

    print(f'# fleet_bench: {args.jobs} jobs, seed {args.seed} '
          f'(jittered run)', file=sys.stderr)
    jittered = run(jitter=True)
    print('# fleet_bench: replay (determinism check)',
          file=sys.stderr)
    replay = run(jitter=True)
    print('# fleet_bench: no-jitter control arm', file=sys.stderr)
    no_jitter = run(jitter=False)

    deterministic = (json.dumps(jittered, sort_keys=True) ==
                     json.dumps(replay, sort_keys=True))
    jitter_peak = jittered['relaunch_concurrency']['max']
    herd_peak = no_jitter['relaunch_concurrency']['max']
    checks = {
        'deterministic_replay': deterministic,
        'all_jobs_succeeded': (
            jittered['final_statuses'] ==
            {'SUCCEEDED': args.jobs}),
        'storm_hit_all_recovered': (
            jittered['storm_hit_jobs'] > 0 and
            jittered['storm_hit_recovered'] ==
            jittered['storm_hit_jobs'] and
            jittered['recovery_events_open'] == 0),
        'jitter_bounds_herd': jitter_peak < herd_peak,
    }

    result = {
        'bench': 'fleet_storm',
        'jobs': args.jobs,
        'seed': args.seed,
        'plan': plan_spec,
        'checks': checks,
        'jittered': jittered,
        'no_jitter': {
            'relaunch_concurrency':
                no_jitter['relaunch_concurrency'],
            'final_statuses': no_jitter['final_statuses'],
            'recovery_latency_s': no_jitter['recovery_latency_s'],
        },
        'herd_peak_ratio': (round(herd_peak / jitter_peak, 3)
                            if jitter_peak else None),
    }
    text = json.dumps(result, indent=1, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, 'w', encoding='utf-8') as f:
            f.write(text + '\n')
        print(f'# wrote {args.out}', file=sys.stderr)
    if not all(checks.values()) and not args.no_assert:
        print(f'# FAILED checks: '
              f'{[k for k, v in checks.items() if not v]}',
              file=sys.stderr)
        sys.exit(1)


if __name__ == '__main__':
    main()
