"""Measure the pallas-flash vs XLA attention crossover on the chip.

VERDICT r3: `_FLASH_MIN_SEQ = 2048` in ops/attention.py is a guess —
the pallas kernel measured ~45ms/step SLOWER than XLA fused attention
at seq=1024 on v5e, but the 2k/4k/8k points were never captured (the
relay wedged). This script times a fwd+bwd GPT-2-block-shaped
attention at several sequence lengths with flash forced ON and OFF and
prints the winner per length, so `_FLASH_MIN_SEQ` can be set from
data:

    python benchmarks/flash_crossover.py            # on the TPU
    python benchmarks/flash_crossover.py --cpu      # smoke the harness
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--cpu', action='store_true')
    parser.add_argument('--batch', type=int, default=8)
    parser.add_argument('--heads', type=int, default=12)
    parser.add_argument('--head-dim', type=int, default=64)
    parser.add_argument('--seqs', type=int, nargs='+',
                        default=[1024, 2048, 4096, 8192])
    parser.add_argument('--steps', type=int, default=10)
    args = parser.parse_args()

    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    results = []
    for seq in args.seqs:
        row = {'seq': seq}
        for mode, min_seq in (('xla', 1 << 30), ('flash', 1)):
            os.environ['SKYPILOT_TPU_FLASH_MIN_SEQ'] = str(min_seq)
            # Re-import so the module-level constant re-reads the env.
            for name in list(sys.modules):
                if name.startswith('skypilot_tpu.ops'):
                    del sys.modules[name]
            from skypilot_tpu.ops import attention as attn

            def loss_fn(q, k, v):
                out = attn.dot_product_attention(q, k, v, causal=True)
                return jnp.sum(out.astype(jnp.float32) ** 2)

            step = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
            shape = (args.batch, seq, args.heads, args.head_dim)
            key = jax.random.PRNGKey(0)
            q = jax.random.normal(key, shape, jnp.bfloat16)
            k = jax.random.normal(key, shape, jnp.bfloat16)
            v = jax.random.normal(key, shape, jnp.bfloat16)
            try:
                out = step(q, k, v)           # compile + correctness
                jax.block_until_ready(out)
                start = time.perf_counter()
                for _ in range(args.steps):
                    out = step(q, k, v)
                jax.block_until_ready(out)
                ms = (time.perf_counter() - start) / args.steps * 1e3
            except Exception as e:  # pylint: disable=broad-except
                print(f'# seq={seq} {mode}: failed '
                      f'({type(e).__name__}: {str(e)[:120]})')
                ms = float('inf')
            row[mode] = ms
            print(f'# seq={seq:5d} {mode:5s}: {ms:8.2f} ms/step (fwd+bwd)',
                  flush=True)
        winner = 'flash' if row['flash'] < row['xla'] else 'xla'
        speedup = (row['xla'] / row['flash']
                   if row['flash'] not in (0, float('inf')) else 0)
        row['winner'] = winner
        results.append(row)
        print(f'= seq={seq}: {winner} wins '
              f'(flash is {speedup:.2f}x vs xla)', flush=True)

    flash_wins = [r['seq'] for r in results if r['winner'] == 'flash']
    if flash_wins:
        print(f'=> set SKYPILOT_TPU_FLASH_MIN_SEQ={min(flash_wins)} '
              f'(ops/attention.py _FLASH_MIN_SEQ)')
    else:
        print('=> XLA fused attention wins at every measured length; '
              'keep _FLASH_MIN_SEQ high (pallas kernel needs tuning '
              'before it pays off here)')


if __name__ == '__main__':
    main()
