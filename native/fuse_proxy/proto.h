// Wire protocol shared by fusermount_shim and fuse_proxy_server.
//
// Reference analog: addons/fuse-proxy (Go) — an unprivileged pod's
// `fusermount` calls are forwarded over a unix socket to a privileged
// daemonset which performs the real mount and hands the opened
// /dev/fuse fd back via SCM_RIGHTS, exactly like setuid fusermount
// hands the fd to libfuse over _FUSE_COMMFD.
//
// Framing (both directions, little-endian):
//   request:  u32 nstrings, then nstrings x (u32 len, bytes) —
//             strings[0] = client cwd, strings[1..] = fusermount argv
//             (without argv[0]).
//   response: u32 status (fusermount exit code, or 200+ for proxy
//             errors); when status == 0 and the operation was a mount,
//             a 1-byte message with the fuse fd attached via
//             SCM_RIGHTS follows.
#pragma once

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

namespace fuse_proxy {

constexpr uint32_t kStatusBadRequest = 200;
constexpr uint32_t kStatusForbidden = 201;
constexpr uint32_t kStatusInternal = 202;
constexpr const char* kDefaultSocket = "/run/fuse-proxy/fuse-proxy.sock";

inline bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool send_strings(int fd, const std::vector<std::string>& strs) {
  uint32_t n = static_cast<uint32_t>(strs.size());
  if (!write_full(fd, &n, sizeof(n))) return false;
  for (const auto& s : strs) {
    uint32_t len = static_cast<uint32_t>(s.size());
    if (!write_full(fd, &len, sizeof(len))) return false;
    if (len > 0 && !write_full(fd, s.data(), len)) return false;
  }
  return true;
}

inline bool recv_strings(int fd, std::vector<std::string>* out,
                         uint32_t max_strings = 256,
                         uint32_t max_len = 1 << 16) {
  uint32_t n = 0;
  if (!read_full(fd, &n, sizeof(n))) return false;
  if (n > max_strings) return false;
  out->clear();
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t len = 0;
    if (!read_full(fd, &len, sizeof(len))) return false;
    if (len > max_len) return false;
    std::string s(len, '\0');
    if (len > 0 && !read_full(fd, s.data(), len)) return false;
    out->push_back(std::move(s));
  }
  return true;
}

// Send one byte with an fd attached (SCM_RIGHTS).
inline bool send_fd(int sock, int fd_to_send) {
  char data = 'F';
  struct iovec iov = {&data, 1};
  char ctrl[CMSG_SPACE(sizeof(int))];
  std::memset(ctrl, 0, sizeof(ctrl));
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = ctrl;
  msg.msg_controllen = sizeof(ctrl);
  struct cmsghdr* cmsg = CMSG_FIRSTHDR(&msg);
  cmsg->cmsg_level = SOL_SOCKET;
  cmsg->cmsg_type = SCM_RIGHTS;
  cmsg->cmsg_len = CMSG_LEN(sizeof(int));
  std::memcpy(CMSG_DATA(cmsg), &fd_to_send, sizeof(int));
  for (;;) {
    ssize_t r = sendmsg(sock, &msg, 0);
    if (r < 0 && errno == EINTR) continue;
    return r == 1;
  }
}

// Receive one byte + attached fd; returns fd or -1.
inline int recv_fd(int sock) {
  char data = 0;
  struct iovec iov = {&data, 1};
  char ctrl[CMSG_SPACE(sizeof(int))];
  std::memset(ctrl, 0, sizeof(ctrl));
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = ctrl;
  msg.msg_controllen = sizeof(ctrl);
  for (;;) {
    ssize_t r = recvmsg(sock, &msg, 0);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return -1;
    break;
  }
  for (struct cmsghdr* cmsg = CMSG_FIRSTHDR(&msg); cmsg != nullptr;
       cmsg = CMSG_NXTHDR(&msg, cmsg)) {
    if (cmsg->cmsg_level == SOL_SOCKET && cmsg->cmsg_type == SCM_RIGHTS) {
      int fd = -1;
      std::memcpy(&fd, CMSG_DATA(cmsg), sizeof(int));
      return fd;
    }
  }
  return -1;
}

inline int connect_unix(const std::string& path) {
  int sock = socket(AF_UNIX, SOCK_STREAM, 0);
  if (sock < 0) return -1;
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  if (path.size() >= sizeof(addr.sun_path)) {
    close(sock);
    return -1;
  }
  std::strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(sock, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(sock);
    return -1;
  }
  return sock;
}

}  // namespace fuse_proxy
