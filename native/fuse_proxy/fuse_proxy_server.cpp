// fuse-proxy server: privileged side of rootless FUSE mounting.
//
// Reference analog: addons/fuse-proxy cmd/fusermount-server (Go,
// runs as a privileged daemonset). Accepts fusermount calls forwarded
// by the shim, translates the client's container-local mountpoint into
// this namespace via /proc/<peer pid>/root (SO_PEERCRED; needs
// hostPID in the daemonset), validates it against an allow-list root,
// runs the REAL fusermount with _FUSE_COMMFD wired to a socketpair,
// captures the opened /dev/fuse fd and ships it back to the shim via
// SCM_RIGHTS.
//
// Mountpoint handling is race-hardened: after validation the
// mountpoint is pinned with an O_PATH|O_NOFOLLOW fd, re-checked
// through /proc/self/fd (check-after-open on a stable fd), and
// fusermount receives the /proc/self/fd/N path — a client swapping
// path components for symlinks between check and mount cannot
// redirect the mount outside the allow-list.
//
// Env:
//   FUSE_PROXY_SOCKET        listen path (default /run/fuse-proxy/..)
//   FUSE_PROXY_ALLOWED_ROOT  mountpoints must resolve under this
//                            (default "/", i.e. allow all)
//   FUSE_PROXY_FUSERMOUNT    real fusermount binary; default tries
//                            fusermount3 then fusermount — tests
//                            point this at a fake to exercise the
//                            protocol without privileges.
#include <climits>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <fcntl.h>
#include <string>
#include <sys/stat.h>
#include <sys/wait.h>
#include <thread>
#include <vector>

#include "proto.h"

using fuse_proxy::kStatusBadRequest;
using fuse_proxy::kStatusForbidden;
using fuse_proxy::kStatusInternal;
using fuse_proxy::recv_fd;
using fuse_proxy::recv_strings;
using fuse_proxy::send_fd;
using fuse_proxy::write_full;

namespace {

std::string g_allowed_root = "/";
std::string g_fusermount;  // empty = default chain

// The client may live in another mount namespace (a task pod); its
// paths are only meaningful through /proc/<pid>/root. With hostPID
// (daemonset) this translates container paths to host paths; for a
// same-namespace client the prefix resolves to "/" and is a no-op.
std::string proc_root_prefix(int client_sock) {
  struct ucred cred = {};
  socklen_t len = sizeof(cred);
  if (getsockopt(client_sock, SOL_SOCKET, SO_PEERCRED, &cred, &len) != 0 ||
      cred.pid <= 0) {
    return "";
  }
  return "/proc/" + std::to_string(cred.pid) + "/root";
}

std::string realpath_str(const std::string& p) {
  char resolved[PATH_MAX];
  if (realpath(p.c_str(), resolved) == nullptr) return "";
  return resolved;
}

// Resolve the client's mountpoint into THIS namespace. For unmounts
// the mountpoint itself may be a dead FUSE endpoint (ENOTCONN), so
// only the parent directory is resolved and the leaf is re-joined.
std::string resolve_mountpoint(const std::string& proc_root,
                               const std::string& cwd,
                               const std::string& arg, bool is_unmount) {
  std::string joined = arg;
  if (!arg.empty() && arg[0] != '/') {
    joined = cwd + "/" + arg;
  }
  joined = proc_root + joined;
  if (!is_unmount) return realpath_str(joined);
  size_t slash = joined.find_last_of('/');
  if (slash == std::string::npos || slash + 1 >= joined.size()) return "";
  std::string leaf = joined.substr(slash + 1);
  if (leaf == "." || leaf == "..") return "";
  std::string parent = realpath_str(joined.substr(0, slash));
  if (parent.empty()) return "";
  return parent == "/" ? parent + leaf : parent + "/" + leaf;
}

bool under_allowed_root(const std::string& path) {
  if (g_allowed_root == "/") return true;
  if (path == g_allowed_root) return true;
  return path.rfind(g_allowed_root + "/", 0) == 0;
}

// The mountpoint is the last non-option argument (after `--` if
// present). Returns its index in argv or -1.
int find_mountpoint_index(const std::vector<std::string>& argv) {
  bool after_dashes = false;
  int last = -1;
  for (size_t i = 0; i < argv.size(); ++i) {
    const std::string& a = argv[i];
    if (!after_dashes && a == "--") {
      after_dashes = true;
      continue;
    }
    if (!after_dashes && !a.empty() && a[0] == '-') {
      if (a == "-o" && i + 1 < argv.size()) ++i;  // skip option value
      continue;
    }
    last = static_cast<int>(i);
  }
  return last;
}

// Run the real fusermount; on success for mounts, *fuse_fd holds the
// captured /dev/fuse fd. `inherit_fd` (if >= 0) is kept open across
// the exec so /proc/self/fd/N mountpoint paths stay valid in the
// child. Returns the child's exit code (or 2xx).
uint32_t run_fusermount(std::vector<std::string> argv, bool is_mount,
                        int* fuse_fd, int inherit_fd) {
  int sp[2] = {-1, -1};
  if (is_mount &&
      socketpair(AF_UNIX, SOCK_STREAM, 0, sp) != 0) {
    return kStatusInternal;
  }
  pid_t pid = fork();
  if (pid < 0) {
    if (is_mount) {
      close(sp[0]);
      close(sp[1]);
    }
    return kStatusInternal;
  }
  if (pid == 0) {  // child: exec fusermount
    if (is_mount) {
      close(sp[0]);
      char buf[16];
      std::snprintf(buf, sizeof(buf), "%d", sp[1]);
      setenv("_FUSE_COMMFD", buf, 1);
    } else {
      unsetenv("_FUSE_COMMFD");
    }
    if (inherit_fd >= 0) {
      // Drop CLOEXEC so the /proc/self/fd/N path survives exec.
      int flags = fcntl(inherit_fd, F_GETFD);
      if (flags >= 0) fcntl(inherit_fd, F_SETFD, flags & ~FD_CLOEXEC);
    }
    std::vector<char*> cargv;
    cargv.push_back(nullptr);  // argv[0], patched per attempt
    for (auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    if (!g_fusermount.empty()) {
      cargv[0] = const_cast<char*>(g_fusermount.c_str());
      execvp(g_fusermount.c_str(), cargv.data());
    } else {
      // Default chain: fuse3's binary first, fuse2's as fallback.
      cargv[0] = const_cast<char*>("fusermount3");
      execvp("fusermount3", cargv.data());
      cargv[0] = const_cast<char*>("fusermount");
      execvp("fusermount", cargv.data());
    }
    std::fprintf(stderr, "fuse-proxy: exec fusermount: %s\n",
                 std::strerror(errno));
    _exit(127);
  }
  // parent
  if (is_mount) {
    close(sp[1]);
    *fuse_fd = recv_fd(sp[0]);  // blocks until fusermount sends it
    close(sp[0]);
  }
  int wstatus = 0;
  while (waitpid(pid, &wstatus, 0) < 0 && errno == EINTR) {
  }
  uint32_t code = WIFEXITED(wstatus)
                      ? static_cast<uint32_t>(WEXITSTATUS(wstatus))
                      : kStatusInternal;
  if (code == 0 && is_mount && *fuse_fd < 0) code = kStatusInternal;
  return code;
}

void handle_client(int client) {
  std::vector<std::string> frame;
  uint32_t status = kStatusBadRequest;
  if (!recv_strings(client, &frame) || frame.size() < 2) {
    write_full(client, &status, sizeof(status));
    return;
  }
  const std::string cwd = frame[0];
  std::vector<std::string> argv(frame.begin() + 1, frame.end());

  bool is_unmount = false;
  for (const auto& a : argv) {
    if (a == "-u") is_unmount = true;
  }
  int mp_idx = find_mountpoint_index(argv);
  if (mp_idx < 0) {
    write_full(client, &status, sizeof(status));
    return;
  }
  std::string proc_root = proc_root_prefix(client);
  std::string resolved = resolve_mountpoint(proc_root, cwd, argv[mp_idx],
                                            is_unmount);
  if (resolved.empty() || !under_allowed_root(resolved)) {
    status = kStatusForbidden;
    std::fprintf(stderr, "fuse-proxy: refused mountpoint %s "
                         "(allowed root %s)\n",
                 argv[mp_idx].c_str(), g_allowed_root.c_str());
    write_full(client, &status, sizeof(status));
    return;
  }

  int pin_fd = -1;
  if (!is_unmount) {
    // Pin the validated directory, then re-check what we actually
    // opened — a client swapping components for symlinks after the
    // realpath cannot move the mount target (TOCTOU).
    pin_fd = open(resolved.c_str(),
                  O_PATH | O_DIRECTORY | O_NOFOLLOW | O_CLOEXEC);
    std::string via_fd =
        pin_fd >= 0
            ? realpath_str("/proc/self/fd/" + std::to_string(pin_fd))
            : "";
    if (pin_fd < 0 || via_fd.empty() || !under_allowed_root(via_fd)) {
      status = kStatusForbidden;
      std::fprintf(stderr, "fuse-proxy: mountpoint %s changed during "
                           "validation\n", resolved.c_str());
      write_full(client, &status, sizeof(status));
      if (pin_fd >= 0) close(pin_fd);
      return;
    }
    argv[mp_idx] = "/proc/self/fd/" + std::to_string(pin_fd);
  } else {
    argv[mp_idx] = resolved;
  }

  int fuse_fd = -1;
  status = run_fusermount(argv, /*is_mount=*/!is_unmount, &fuse_fd,
                          pin_fd);
  if (pin_fd >= 0) close(pin_fd);
  write_full(client, &status, sizeof(status));
  if (status == 0 && !is_unmount && fuse_fd >= 0) {
    send_fd(client, fuse_fd);
  }
  if (fuse_fd >= 0) close(fuse_fd);
}

}  // namespace

int main() {
  signal(SIGPIPE, SIG_IGN);
  const char* sock_path = std::getenv("FUSE_PROXY_SOCKET");
  if (sock_path == nullptr) sock_path = fuse_proxy::kDefaultSocket;
  const char* root = std::getenv("FUSE_PROXY_ALLOWED_ROOT");
  if (root != nullptr) g_allowed_root = root;
  const char* fm = std::getenv("FUSE_PROXY_FUSERMOUNT");
  if (fm != nullptr) g_fusermount = fm;

  unlink(sock_path);
  int listener = socket(AF_UNIX, SOCK_STREAM, 0);
  if (listener < 0) {
    std::perror("fuse-proxy: socket");
    return 1;
  }
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (bind(listener, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0 ||
      listen(listener, 16) != 0) {
    std::perror("fuse-proxy: bind/listen");
    return 1;
  }
  chmod(sock_path, 0666);  // task pods run as arbitrary uids
  std::fprintf(stderr, "fuse-proxy: listening on %s (root %s, "
                       "fusermount %s)\n",
               sock_path, g_allowed_root.c_str(),
               g_fusermount.empty() ? "fusermount3|fusermount"
                                    : g_fusermount.c_str());

  for (;;) {
    int client = accept(listener, nullptr, nullptr);
    if (client < 0) {
      if (errno == EINTR) continue;
      std::perror("fuse-proxy: accept");
      return 1;
    }
    // One thread per client: a hung fusermount (or a client stalled
    // mid-frame) must not block other pods' mounts on this node.
    std::thread([client] {
      handle_client(client);
      close(client);
    }).detach();
  }
}
