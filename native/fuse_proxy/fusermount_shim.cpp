// fusermount shim: drop-in `fusermount`/`fusermount3` for
// unprivileged containers.
//
// Reference analog: addons/fuse-proxy cmd/fusermount-shim (Go).
// libfuse execs `fusermount3 -o <opts> -- <mountpoint>` with
// _FUSE_COMMFD pointing at a socketpair and expects the opened
// /dev/fuse fd back over it. This shim has no privileges; it forwards
// the whole call (argv + cwd) to the fuse-proxy server's unix socket,
// receives the fuse fd via SCM_RIGHTS, and relays it to libfuse over
// _FUSE_COMMFD — indistinguishable from real fusermount to the caller.
//
// Env:
//   FUSE_PROXY_SOCKET  server socket (default /run/fuse-proxy/...)
//   _FUSE_COMMFD       set by libfuse for mounts; absent for -u.
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "proto.h"

using fuse_proxy::connect_unix;
using fuse_proxy::kDefaultSocket;
using fuse_proxy::read_full;
using fuse_proxy::recv_fd;
using fuse_proxy::recv_strings;
using fuse_proxy::send_fd;
using fuse_proxy::send_strings;

int main(int argc, char** argv) {
  const char* sock_path = std::getenv("FUSE_PROXY_SOCKET");
  if (sock_path == nullptr) sock_path = kDefaultSocket;

  int server = connect_unix(sock_path);
  if (server < 0) {
    std::fprintf(stderr,
                 "fusermount-shim: cannot reach fuse-proxy at %s: %s\n",
                 sock_path, std::strerror(errno));
    return 1;
  }

  char cwd_buf[4096];
  if (getcwd(cwd_buf, sizeof(cwd_buf)) == nullptr) cwd_buf[0] = '\0';

  std::vector<std::string> frame;
  frame.emplace_back(cwd_buf);
  for (int i = 1; i < argc; ++i) frame.emplace_back(argv[i]);
  if (!send_strings(server, frame)) {
    std::fprintf(stderr, "fusermount-shim: send failed\n");
    return 1;
  }

  uint32_t status = fuse_proxy::kStatusInternal;
  if (!read_full(server, &status, sizeof(status))) {
    std::fprintf(stderr, "fusermount-shim: server hung up\n");
    return 1;
  }
  if (status != 0) {
    std::fprintf(stderr, "fusermount-shim: proxy status %u\n", status);
    return status >= 200 ? 1 : static_cast<int>(status);
  }

  // Mounts carry the fuse fd back; unmounts don't (no _FUSE_COMMFD).
  const char* commfd_env = std::getenv("_FUSE_COMMFD");
  bool expect_fd = commfd_env != nullptr;
  for (int i = 1; i < argc; ++i) {
    if (std::string(argv[i]) == "-u") expect_fd = false;
  }
  if (!expect_fd) {
    close(server);
    return 0;
  }

  int fuse_fd = recv_fd(server);
  close(server);
  if (fuse_fd < 0) {
    std::fprintf(stderr, "fusermount-shim: no fd from proxy\n");
    return 1;
  }
  int commfd = std::atoi(commfd_env);
  if (!send_fd(commfd, fuse_fd)) {
    std::fprintf(stderr, "fusermount-shim: relay to _FUSE_COMMFD=%d "
                         "failed: %s\n", commfd, std::strerror(errno));
    close(fuse_fd);
    return 1;
  }
  close(fuse_fd);
  return 0;
}
