// token_loader: memory-mapped token-shard reader with prefetch.
//
// The native data path for training recipes (the role the reference
// delegates to Ray/torch dataloaders; here a small C++ core feeds the
// JAX input pipeline). Shards are flat binary files of uint16 or
// uint32 token ids (nanoGPT's .bin format). The loader memory-maps
// every shard, and worker threads fill a ring of pinned host buffers
// with deterministic pseudo-random (or sequential) windows so
// `next_batch` never blocks on disk in steady state.
//
// Multi-host contract: pass (rank, world_size) and every host draws a
// disjoint deterministic stream — the same (seed, step) schedule the
// JAX data-parallel axis expects.
//
// C ABI (ctypes-consumed; see skypilot_tpu/data/token_loader.py):
//   tl_open(paths, n, dtype_bytes)            -> handle
//   tl_total_tokens(handle)                   -> u64
//   tl_start(handle, batch, seq, seed, rank, world, shuffle, nthreads)
//   tl_next(handle, out_u32)                  -> step index (or -1)
//   tl_close(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <queue>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Shard {
  const uint8_t* data = nullptr;
  size_t bytes = 0;
  uint64_t tokens = 0;
};

// splitmix64: tiny deterministic PRNG good enough for window sampling.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Loader {
  std::vector<Shard> shards;
  std::vector<uint64_t> cum_tokens;  // prefix sums for global indexing
  uint64_t total_tokens = 0;
  int dtype_bytes = 2;

  // iteration config
  int batch = 0, seq = 0;
  uint64_t seed = 0;
  int rank = 0, world = 1;
  bool shuffle = true;

  // prefetch ring
  std::vector<std::vector<uint32_t>> ring;
  std::queue<int> free_slots, ready_slots;
  std::vector<int64_t> slot_step;
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  std::vector<std::thread> workers;
  std::atomic<int64_t> next_step{0};
  std::atomic<bool> stop{false};
  int64_t consumer_slot = -1;

  ~Loader() { shutdown(); }

  void shutdown() {
    stop.store(true);
    cv_free.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (auto& s : shards)
      if (s.data) munmap(const_cast<uint8_t*>(s.data), s.bytes);
    shards.clear();
  }

  uint32_t token_at(uint64_t global_idx) const {
    // binary search shard
    size_t lo = 0, hi = shards.size();
    while (lo + 1 < hi) {
      size_t mid = (lo + hi) / 2;
      if (cum_tokens[mid] <= global_idx) lo = mid; else hi = mid;
    }
    uint64_t off = global_idx - cum_tokens[lo];
    const uint8_t* p = shards[lo].data + off * dtype_bytes;
    if (dtype_bytes == 2) {
      uint16_t v;
      std::memcpy(&v, p, 2);
      return v;
    }
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
  }

  void fill_window(uint64_t start, uint32_t* out, int count) const {
    // Fast path: window within one shard → single memcpy-ish loop.
    for (int i = 0; i < count; ++i)
      out[i] = token_at(start + i);
  }

  void fill_batch(int64_t step, uint32_t* out) const {
    const uint64_t n_windows = total_tokens / (uint64_t)seq;
    for (int b = 0; b < batch; ++b) {
      uint64_t start;
      if (shuffle) {
        uint64_t key = splitmix64(
            seed ^ (uint64_t)step * 0x10001ULL ^
            ((uint64_t)rank << 40) ^ (uint64_t)b);
        start = key % (total_tokens - (uint64_t)seq - 1);
      } else {
        uint64_t window =
            ((uint64_t)step * (uint64_t)world + (uint64_t)rank) *
                (uint64_t)batch + (uint64_t)b;
        start = (window % n_windows) * (uint64_t)seq;
        if (start + seq + 1 > total_tokens)
          start = total_tokens - seq - 1;
      }
      // +1: targets are inputs shifted by one (LM objective).
      fill_window(start, out + (size_t)b * (seq + 1), seq + 1);
    }
  }

  void worker_loop() {
    while (!stop.load()) {
      int slot;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_free.wait(lock, [&] { return stop.load() || !free_slots.empty(); });
        if (stop.load()) return;
        slot = free_slots.front();
        free_slots.pop();
      }
      int64_t step = next_step.fetch_add(1);
      fill_batch(step, ring[slot].data());
      {
        std::unique_lock<std::mutex> lock(mu);
        slot_step[slot] = step;
        ready_slots.push(slot);
      }
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* tl_open(const char** paths, int n, int dtype_bytes) {
  auto* loader = new Loader();
  loader->dtype_bytes = dtype_bytes;
  loader->cum_tokens.push_back(0);
  for (int i = 0; i < n; ++i) {
    int fd = ::open(paths[i], O_RDONLY);
    if (fd < 0) {
      delete loader;
      return nullptr;
    }
    struct stat st;
    fstat(fd, &st);
    Shard shard;
    shard.bytes = (size_t)st.st_size;
    shard.tokens = shard.bytes / dtype_bytes;
    shard.data = (const uint8_t*)mmap(nullptr, shard.bytes, PROT_READ,
                                      MAP_PRIVATE, fd, 0);
    ::close(fd);
    if (shard.data == MAP_FAILED) {
      delete loader;
      return nullptr;
    }
    madvise(const_cast<uint8_t*>(shard.data), shard.bytes, MADV_RANDOM);
    loader->total_tokens += shard.tokens;
    loader->shards.push_back(shard);
    loader->cum_tokens.push_back(loader->total_tokens);
  }
  return loader;
}

uint64_t tl_total_tokens(void* handle) {
  return ((Loader*)handle)->total_tokens;
}

int tl_start(void* handle, int batch, int seq, uint64_t seed, int rank,
             int world, int shuffle, int nthreads, int ring_slots) {
  auto* loader = (Loader*)handle;
  if ((uint64_t)(seq + 1) >= loader->total_tokens) return -1;
  loader->batch = batch;
  loader->seq = seq;
  loader->seed = seed;
  loader->rank = rank;
  loader->world = world;
  loader->shuffle = shuffle != 0;
  if (ring_slots < 2) ring_slots = 2;
  loader->ring.assign(ring_slots,
                      std::vector<uint32_t>((size_t)batch * (seq + 1)));
  loader->slot_step.assign(ring_slots, -1);
  for (int i = 0; i < ring_slots; ++i) loader->free_slots.push(i);
  if (nthreads < 1) nthreads = 1;
  for (int i = 0; i < nthreads; ++i)
    loader->workers.emplace_back([loader] { loader->worker_loop(); });
  return 0;
}

int64_t tl_next(void* handle, uint32_t* out) {
  auto* loader = (Loader*)handle;
  int slot;
  {
    std::unique_lock<std::mutex> lock(loader->mu);
    // Return the previous slot to the free pool.
    if (loader->consumer_slot >= 0) {
      loader->free_slots.push((int)loader->consumer_slot);
      loader->cv_free.notify_one();
    }
    loader->cv_ready.wait(lock, [&] {
      return loader->stop.load() || !loader->ready_slots.empty();
    });
    if (loader->stop.load()) return -1;
    slot = loader->ready_slots.front();
    loader->ready_slots.pop();
    loader->consumer_slot = slot;
  }
  std::memcpy(out, loader->ring[slot].data(),
              loader->ring[slot].size() * sizeof(uint32_t));
  return loader->slot_step[slot];
}

void tl_close(void* handle) { delete (Loader*)handle; }

}  // extern "C"
